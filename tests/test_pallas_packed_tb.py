"""Temporal-blocked packed kernel (ops/pallas_packed_tb.py) vs jnp.

Round 12: the kernel is a DEPTH-k BUILDER — k Yee steps per HBM pass
(k in {2, 3, 4}; 2k phases, per-generation VMEM rings, k-generation
CPML psi recursion, ~48/k B/cell/step f32) with a VMEM-calibrated
auto-depth picker (deepest viable k; ``FDTD3D_TB_DEPTH`` pins) and
WIDENED eligibility: in-kernel TFSF plane-value corrections, electric-
Drude ADE J in the ring scratch, and material grids as per-generation
tiled operands all run at blocked speed instead of falling back.
Parity with the jnp step must hold at f32 roundoff INCLUDING the psi
recursion (and Drude J) state, for k-divisible AND non-divisible step
counts (the tail appends n mod k single-step ``pallas_packed`` calls
at the SAME tile) and for odd / two-region tilings (pipeline-drain
edges). ``FDTD3D_NO_TEMPORAL=1`` is the escape hatch that forces the
round-6 single-step kernel bit-for-bit.

Coverage split (tier-1 wall budget, PR 4/9 precedent): tier-1 spreads
the widened scenarios across depths (TFSF@k3, Drude@k4, grids@k2) so
every scenario and every depth is exercised once; the full scenario x
depth matrix rides the slow lane.
"""

import os

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, OutputConfig,
                               ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3)

DEPTHS = (2, 3, 4)


@pytest.fixture
def tb_depth(monkeypatch):
    """Pin the pipeline depth for one test via the registered knob."""
    def pin(k):
        if k is None:
            monkeypatch.delenv("FDTD3D_TB_DEPTH", raising=False)
        else:
            monkeypatch.setenv("FDTD3D_TB_DEPTH", str(k))
    return pin


def _seed_fields(sim, seed=0):
    key = jax.random.PRNGKey(seed)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))


def _run(use_pallas, seed=0, **kw):
    cfg = dict(BASE, use_pallas=use_pallas, **kw)
    sim = Simulation(SimConfig(**cfg))
    _seed_fields(sim, seed=seed)
    sim.run()
    return sim


def _parity(tol=2e-6, seed=0, psi=True, depth=None, extra_state=(),
            **kw):
    j = _run(False, seed=seed, **kw)
    p = _run(True, seed=seed, **kw)
    assert p.step_kind == "pallas_packed_tb", p.step_kind
    if depth is not None:
        assert p.step_diag["temporal_block"] == depth
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"
    groups = (("psi_E", "psi_H") if psi and "psi_E" in j.state else ())
    for grp in tuple(groups) + tuple(extra_state):
        for k in j.state[grp]:
            a = np.asarray(j.state[grp][k])
            b = np.asarray(p.state[grp][k])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < tol, f"{grp}/{k}: rel {rel:.2e}"
    return j, p


@pytest.mark.parametrize("k", DEPTHS)
def test_tb_vacuum_parity(tb_depth, k):
    tb_depth(k)
    _parity(depth=k)


@pytest.mark.slow
def test_tb_cpml_parity_even():
    """Subsumed in tier-1 by test_tb_odd_ntiles_and_two_region_x_psi
    (even horizon + full CPML at a two-region tiling); kept in the slow
    lane as the minimal single-region repro (auto depth)."""
    _parity(pml=PmlConfig(size=(3, 3, 3)))


@pytest.mark.parametrize("k", (3, 4))
def test_tb_cpml_parity_tail_steps(tb_depth, k):
    """Non-divisible horizon: n//k blocked passes + n mod k single-step
    tails on the identical packed-carry layout inside ONE compiled
    chunk (solver.make_chunk_runner) — 7 steps = 2x3+1 at k=3,
    1x4+3 at k=4. k=2 rides the two-region test below."""
    tb_depth(k)
    _parity(pml=PmlConfig(size=(3, 3, 3)), time_steps=7, depth=k)


def test_tb_odd_ntiles_and_two_region_x_psi():
    """48-long x at tile 16 -> 3 tiles with the two-region tile-aligned
    x-psi layout (interior tile pins its block; lag-2(k-1)/lag-(2k-1)
    output maps): the pipeline-drain edges the ISSUE names. Auto depth:
    the picker must choose the DEEPEST viable k here (the VMEM model
    affords tile >= 2 at every depth on this grid)."""
    j, p = _parity(pml=PmlConfig(size=(3, 3, 3)), size=(48, 16, 16))
    assert p.step_diag["temporal_block"] == max(DEPTHS)
    pick = p.step_diag["depth_pick"]
    assert pick["source"] == "auto"
    assert set(pick["candidates"]) == set(DEPTHS)


def test_tb_two_region_odd_steps_sourced(tb_depth):
    tb_depth(2)
    _parity(pml=PmlConfig(size=(3, 3, 3)), size=(48, 16, 16),
            time_steps=7, depth=2,
            point_source=PointSourceConfig(enabled=True, component="Ey",
                                           position=(30, 8, 8)))


@pytest.mark.slow
def test_tb_point_source_parity_even():
    """The mid-grid injection rides IN-KERNEL (every E phase adds the
    masked waveform term at its generation's lag — a post-patch cannot
    reach the later steps' curls). Tier-1 coverage of that path lives
    in test_tb_two_region_odd_steps_sourced; this pure-even
    single-region variant rides the slow lane (tier-1 wall budget)."""
    src = PointSourceConfig(enabled=True, component="Ez",
                            position=(8, 8, 8))
    _parity(pml=PmlConfig(size=(3, 3, 3)), point_source=src)


@pytest.mark.slow
def test_tb_x_only_and_yz_only_pml():
    """Axis-isolated CPML parities — a debugging decomposition of the
    full-PML parity above (which exercises both mechanisms at once);
    slow lane for the tier-1 wall budget."""
    _parity(pml=PmlConfig(size=(3, 0, 0)))   # fused-x path alone
    _parity(pml=PmlConfig(size=(0, 3, 3)))   # y/z slab recursions alone


@pytest.mark.slow
def test_tb_bf16_smoke():
    """Slow lane (tier-1 wall budget): the acceptance parity gate is
    f32; bench's accuracy spot-check covers bf16 on chip windows."""
    _parity(tol=3e-2, psi=False, dtype="bfloat16",
            pml=PmlConfig(size=(3, 3, 3)))


def test_tb_escape_hatch_bit_for_bit(monkeypatch):
    """FDTD3D_NO_TEMPORAL must force the round-6 kernel: same kind and
    BIT-identical fields as a dispatch where the tb builder is absent
    entirely (the acceptance criterion's escape hatch)."""
    kw = dict(pml=PmlConfig(size=(3, 3, 3)))
    with monkeypatch.context() as m:
        m.setenv("FDTD3D_NO_TEMPORAL", "1")
        a = _run(True, **kw)
    assert a.step_kind == "pallas_packed", a.step_kind

    from fdtd3d_tpu.ops import pallas_packed_tb
    with monkeypatch.context() as m:
        m.setattr(pallas_packed_tb, "make_packed_tb_step",
                  lambda *args, **kwargs: None)
        b = _run(True, **kw)
    assert b.step_kind == "pallas_packed", b.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        assert np.array_equal(np.asarray(a.field(c)),
                              np.asarray(b.field(c))), c


# -------------------------------------------------------------------------
# the VMEM-calibrated auto-depth picker
# -------------------------------------------------------------------------

def test_tb_depth_pick_env_pin(tb_depth):
    """FDTD3D_TB_DEPTH pins the pipeline depth; the decision record
    names the env source; out-of-domain values are a config error."""
    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed_tb
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    tb_depth(3)
    step = pallas_packed_tb.make_packed_tb_step(static)
    assert step.steps_per_call == 3
    assert step.diag["temporal_block"] == 3
    assert step.diag["depth_pick"]["source"] == "env:FDTD3D_TB_DEPTH=3"
    tb_depth(None)
    os.environ["FDTD3D_TB_DEPTH"] = "5"
    try:
        with pytest.raises(ValueError, match="FDTD3D_TB_DEPTH"):
            pallas_packed_tb.pick_depth(static)
    finally:
        del os.environ["FDTD3D_TB_DEPTH"]


def test_tb_depth_pick_downgrades_on_vmem(monkeypatch):
    """The calibration-table knob drives the depth ladder: a k=4 temps
    row too large for any tile must downgrade the AUTO pick to k=3
    (k -> k-1 before leaving the kernel family), and poisoning k=3
    too must land on k=2."""
    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed_tb
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    monkeypatch.setenv("FDTD3D_VMEM_TEMPS_TABLE", "tb4=99999999")
    k, tile, cands, source = pallas_packed_tb.pick_depth(static)
    assert k == 3 and cands[4] == 0 and source == "auto"
    monkeypatch.setenv("FDTD3D_VMEM_TEMPS_TABLE",
                       "tb4=99999999,tb3=99999999")
    k2, _, cands2, _ = pallas_packed_tb.pick_depth(static)
    assert k2 == 2 and cands2[3] == 0
    monkeypatch.setenv("FDTD3D_VMEM_TEMPS_TABLE", "bogus=1")
    with pytest.raises(ValueError, match="FDTD3D_VMEM_TEMPS_TABLE"):
        pallas_packed_tb.pick_depth(static)


def test_tb_pinned_depth_not_viable_is_named_error(monkeypatch):
    """Review finding: an explicit FDTD3D_TB_DEPTH pin the VMEM model
    (or a thin sharded wedge) cannot honor must raise a NAMED config
    error, never silently dispatch the 48 B/cell single-step kernel —
    a user A/B-ing depths would blame the kernel for the fallback."""
    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed_tb
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "4")
    monkeypatch.setenv("FDTD3D_VMEM_TEMPS_TABLE", "tb4=99999999")
    with pytest.raises(ValueError, match="FDTD3D_TB_DEPTH=4"):
        pallas_packed_tb.pick_depth(static)
    with pytest.raises(ValueError, match="FDTD3D_TB_DEPTH=4"):
        Simulation(cfg)
    # the AUTO pick under the same poisoned table still degrades
    # gracefully to k=3 (the depth ladder, not an error)
    monkeypatch.delenv("FDTD3D_TB_DEPTH")
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_tb"
    assert sim.step_diag["temporal_block"] == 3


def test_tb_vmem_temps_table_central():
    """Satellite 1: the scattered per-module temps constants are gone —
    every kernel kind reads the ONE config table."""
    from fdtd3d_tpu import config as config_mod
    from fdtd3d_tpu.ops import pallas_packed, pallas_packed_tb
    for k in DEPTHS:
        assert config_mod.vmem_temps("tb", k) == \
            config_mod.VMEM_TEMPS_DEFAULTS[f"tb{k}"]
    assert config_mod.vmem_temps("packed") == 25   # the MEASURED row
    assert not hasattr(pallas_packed, "_TEMPS_F32_PER_CELL")
    assert not hasattr(pallas_packed_tb, "_TEMPS_F32_PER_CELL_TB")


# -------------------------------------------------------------------------
# sharded: the depth-k halo pipeline
# -------------------------------------------------------------------------

def _sharded_parity(topo, steps, tol=2e-6, seed=0, depth=None, **kw):
    """tb vs jnp on the SAME topology (per-shard slab-compacted psi
    layouts coincide), fields AND psi recursion state. Seeded fields +
    interior source: a bare Ez point source leaves Hz identically zero
    by symmetry, and comparing that component's roundoff noise against
    itself is a degenerate metric."""
    from fdtd3d_tpu.parallel import distributed as pdist
    par = ParallelConfig(topology="manual", manual_topology=topo)
    base = dict(BASE, time_steps=steps, pml=PmlConfig(size=(2, 2, 2)),
                point_source=PointSourceConfig(
                    enabled=True, component="Ez", position=(8, 8, 8)),
                parallel=par, **kw)
    j = Simulation(SimConfig(**dict(base, use_pallas=False)))
    _seed_fields(j, seed=seed)
    j.run()
    p = Simulation(SimConfig(**dict(base, use_pallas=True)))
    _seed_fields(p, seed=seed)
    p.run()
    assert p.step_kind == "pallas_packed_tb", p.step_kind
    if depth is not None:
        assert p.step_diag["temporal_block"] == depth
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e} on {topo}"
    for grp in ("psi_E", "psi_H"):
        for k in j.state[grp]:
            a = np.asarray(pdist.gather_to_host(j.state[grp][k]))
            b = np.asarray(pdist.gather_to_host(p.state[grp][k]))
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < tol, f"{grp}/{k}: rel {rel:.2e} on {topo}"
    return j, p


def test_tb_sharded_parity_222_even_auto():
    """ISSUE-11 acceptance: sharded tb vs sharded jnp on the (2,2,2)
    CPU interpret mesh at the AUTO depth pick (deepest viable — the
    k-generation boundary-wedge pre-pass and 2k-message exchange),
    even horizon, CPML + interior source."""
    _, p = _sharded_parity((2, 2, 2), steps=8)
    assert p.step_diag["temporal_block"] == max(DEPTHS)
    strat = p.step_diag["comm_strategy"]
    assert strat["ghost_depth"] == p.step_diag["temporal_block"]


def test_tb_sharded_parity_222_odd_k3(tb_depth):
    """Non-divisible horizon under sharding at k=3: 2 blocked passes +
    ONE single-step sharded pallas_packed tail on the same packed
    carry inside one chunk."""
    tb_depth(3)
    _sharded_parity((2, 2, 2), steps=7, depth=3)


def test_tb_sharded_parity_122_k2(tb_depth):
    tb_depth(2)
    _sharded_parity((1, 2, 2), steps=8, depth=2)
    _sharded_parity((1, 2, 2), steps=7, depth=2)


@pytest.mark.slow
def test_tb_sharded_parity_depth_matrix(tb_depth):
    """Full topology x depth matrix (tier-1 spreads one depth per
    topology; the rest rides here)."""
    for k in DEPTHS:
        tb_depth(k)
        _sharded_parity((2, 2, 2), steps=8, depth=k)
        _sharded_parity((2, 1, 1), steps=8, depth=k)
        _sharded_parity((1, 2, 2), steps=7, depth=k)


def test_tb_sharded_odd_ntiles_drain_edges(tb_depth):
    """Odd-ntiles two-region tiling UNDER sharding: 48-long x sharded
    by 2 -> 24 local at tile 8 (3 tiles, two-region x-psi) — the
    pipeline-drain edges masked against the k-deep ghost region (the
    exchanged generation ghosts replace the PEC zeros at the i == 2g-2
    lo edges). x-sharded (2,1,1) isolates the xgh*/xe* operands at
    k=3; (2,2,2) composes them with the y/z thin-block ghosts at
    k=2."""
    from fdtd3d_tpu.parallel import distributed as pdist  # noqa: F401
    for topo, k in (((2, 1, 1), 3), ((2, 2, 2), 2)):
        tb_depth(k)
        par = ParallelConfig(topology="manual", manual_topology=topo)
        base = dict(BASE, size=(48, 16, 16), time_steps=7,
                    pml=PmlConfig(size=(2, 2, 2)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ey",
                        position=(30, 8, 8)),
                    parallel=par)
        j = Simulation(SimConfig(**dict(base, use_pallas=False)))
        _seed_fields(j, seed=3)
        j.run()
        p = Simulation(SimConfig(**dict(base, use_pallas=True)))
        _seed_fields(p, seed=3)
        p.run()
        assert p.step_kind == "pallas_packed_tb", (topo, p.step_kind)
        assert p.step_diag["temporal_block"] == k
        nt = (48 // topo[0]) // p.step_diag["tile"]["EH"]
        assert nt == 3, nt   # odd ntiles: real drain-edge coverage
        for c in ("Ey", "Hz", "Hx"):
            a = np.asarray(j.field(c), np.float32)
            b = np.asarray(p.field(c), np.float32)
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-6, f"{c}: rel {rel:.2e} on {topo} k={k}"


def test_tb_thin_shard_caps_wedge_depth():
    """Review-found regression: a thin sharded axis (16 cells over 8
    shards -> local extent 2) cannot hold a depth-4 boundary wedge
    (generation 1 computes planes [0, k-2]); the auto pick must CAP k
    at the deepest fitting depth (k-1 <= local extent -> k=3 here)
    instead of crashing the trace and burning the VMEM ladder."""
    from fdtd3d_tpu.parallel import distributed as pdist
    par = ParallelConfig(topology="manual", manual_topology=(1, 8, 1))
    base = dict(BASE, pml=PmlConfig(size=(0, 0, 0)),
                point_source=PointSourceConfig(
                    enabled=True, component="Ez", position=(8, 8, 8)),
                parallel=par)
    p = Simulation(SimConfig(**dict(base, use_pallas=True)))
    assert p.step_kind == "pallas_packed_tb", p.step_kind
    assert p.step_diag["temporal_block"] == 3   # capped by the wedge
    assert p.step_diag["depth_pick"]["candidates"][4] == 0
    _seed_fields(p, seed=1)
    p.run()
    j = Simulation(SimConfig(**dict(base, use_pallas=False)))
    _seed_fields(j, seed=1)
    j.run()
    for c in ("Ez", "Hx", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"
    del pdist  # imported for parity with the other sharded tests


def test_tb_sharded_comm_strategy_in_diag(tb_depth):
    """The step's diag carries the planned CommStrategy record (what
    telemetry run_start and the ledger comm lane echo), with
    ghost_depth the scored pipeline depth."""
    tb_depth(3)
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 2))))
    assert sim.step_kind == "pallas_packed_tb"
    strat = sim.step_diag["comm_strategy"]
    assert strat["ghost_depth"] == 3
    assert strat["split"] == "fused" and strat["schedule"] == "async"


def test_tb_sharded_strategy_override_parity(monkeypatch):
    """FDTD3D_COMM_STRATEGY=per-plane,sync must change the message
    plan WITHOUT changing the physics: parity still holds and the
    strategy records the env source."""
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "per-plane,sync")
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "3")
    _, p = _sharded_parity((1, 2, 2), steps=6, depth=3)
    strat = p.step_diag["comm_strategy"]
    assert strat["split"] == "per-plane"
    assert strat["schedule"] == "sync"
    assert strat["source"] == "env:FDTD3D_COMM_STRATEGY"


# -------------------------------------------------------------------------
# round-14 widened SHARDED scenarios: the wedge pre-pass's three new
# ports (incident line / J ring / tiled coefficients)
# -------------------------------------------------------------------------

WIDENED_KW = {
    "tfsf": dict(pml=PmlConfig(size=(2, 2, 2)),
                 tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2))),
    "drude": dict(pml=PmlConfig(size=(0, 2, 2)),
                  materials=MaterialsConfig(
                      use_drude=True, eps_inf=1.5, omega_p=1e11,
                      gamma=1e10,
                      drude_sphere=SphereConfig(enabled=True,
                                                center=(8, 8, 8),
                                                radius=3))),
    "grid": dict(pml=PmlConfig(size=(2, 2, 2)),
                 materials=MaterialsConfig(
                     eps=2.0,
                     eps_sphere=SphereConfig(enabled=True,
                                             center=(8, 8, 8),
                                             radius=4, value=6.0))),
}


def _sharded_widened_parity(monkeypatch, topo, scenario, steps=8,
                            depth=None, seed=0, tol=2e-6,
                            extra_state=()):
    """ISSUE-14 acceptance: a widened sharded scenario dispatches
    ``pallas_packed_tb`` and matches BOTH the jnp step and the
    single-step ``pallas_packed`` reference (FDTD3D_NO_TEMPORAL) at
    f32 roundoff — fields, psi recursion state and (Drude) J — over a
    MULTI-CHUNK run (two advance() calls, non-divisible first chunk
    when steps allows)."""
    from fdtd3d_tpu.parallel import distributed as pdist
    par = ParallelConfig(topology="manual", manual_topology=topo)
    base = dict(BASE, time_steps=steps, parallel=par,
                **WIDENED_KW[scenario])

    def run(use_pallas, no_temporal=False):
        if no_temporal:
            monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")
        else:
            monkeypatch.delenv("FDTD3D_NO_TEMPORAL", raising=False)
        sim = Simulation(SimConfig(**dict(base, use_pallas=use_pallas)))
        _seed_fields(sim, seed=seed)
        n1 = steps // 2 + (steps % 2)
        sim.advance(n1)                 # multi-chunk: two compiled
        sim.advance(steps - n1)         # chunk lengths
        return sim

    j = run(False)
    pk = run(True, no_temporal=True)
    assert pk.step_kind == "pallas_packed", pk.step_kind
    assert pk.step_diag["tb_fallback"]["reason"] == \
        "env:FDTD3D_NO_TEMPORAL"
    p = run(True)
    assert p.step_kind == "pallas_packed_tb", (scenario, p.step_kind)
    if depth is not None:
        assert p.step_diag["temporal_block"] == depth
    assert "tb_fallback" not in (p.step_diag or {})
    for ref, tag in ((j, "jnp"), (pk, "packed")):
        for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
            a = np.asarray(pdist.gather_to_host(ref.field(c)),
                           np.float32)
            b = np.asarray(pdist.gather_to_host(p.field(c)),
                           np.float32)
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < tol, \
                f"{scenario} {c} vs {tag}: rel {rel:.2e} on {topo}"
    for grp in ("psi_E", "psi_H") + tuple(extra_state):
        if grp not in j.state:
            continue
        for key in j.state[grp]:
            a = np.asarray(pdist.gather_to_host(j.state[grp][key]))
            b = np.asarray(pdist.gather_to_host(p.state[grp][key]))
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < tol, \
                f"{scenario} {grp}/{key}: rel {rel:.2e} on {topo}"
    return p


def test_tb_sharded_tfsf_widened_k2(monkeypatch, tb_depth):
    """Sharded TFSF through the wedge incident-line port at k=2 on
    (2,2,2) — tier-1 representative; more depths/topologies in the
    slow-lane matrix."""
    tb_depth(2)
    _sharded_widened_parity(monkeypatch, (2, 2, 2), "tfsf", depth=2)


def test_tb_sharded_drude_widened_k3(monkeypatch, tb_depth):
    """Sharded electric-Drude through the wedge J ring at k=3 on
    (1,2,2), including the J state (the drude sphere also makes
    ca/cb/bj per-cell GRIDS, so the tiled-coefficient port is
    exercised in the same run). Odd horizon: blocked passes + a
    sharded single-step tail."""
    tb_depth(3)
    _sharded_widened_parity(monkeypatch, (1, 2, 2), "drude", steps=7,
                            depth=3, extra_state=("J",))


def test_tb_sharded_material_grid_widened_k2(monkeypatch, tb_depth):
    """Sharded material grids (eps sphere -> 3D ca/cb) through the
    wedge's per-cell coefficient sub-blocks at k=2 on (2,1,1) — the
    x-sharded wedge slices the grids along the tiled axis."""
    tb_depth(2)
    _sharded_widened_parity(monkeypatch, (2, 1, 1), "grid", depth=2)


@pytest.mark.slow
def test_tb_sharded_widened_matrix(monkeypatch, tb_depth):
    """The full widened-scenario x depth x topology matrix (tier-1
    spreads one representative per scenario)."""
    for k in (2, 3):
        for scenario in ("tfsf", "drude", "grid"):
            for topo in ((2, 2, 2), (1, 2, 2)):
                tb_depth(k)
                _sharded_widened_parity(
                    monkeypatch, topo, scenario, depth=k,
                    extra_state=("J",) if scenario == "drude" else ())


# -------------------------------------------------------------------------
# eligibility: widened scenarios dispatch tb; the rest stays on packed
# -------------------------------------------------------------------------

def test_tb_tfsf_in_kernel_parity(tb_depth):
    """ISSUE-11 acceptance: a TFSF scenario dispatches the temporal-
    blocked kernel (in-kernel plane-value corrections at every
    generation's lag) with parity vs jnp — tier-1 representative at
    k=3; the full depth matrix rides the slow lane."""
    tb_depth(3)
    _parity(pml=PmlConfig(size=(3, 3, 3)), depth=3,
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)))


def test_tb_drude_ring_scratch_parity(tb_depth):
    """ISSUE-11 acceptance: a Drude scenario (sphere -> kj/bj/ca/cb
    GRIDS + the J ADE state in the ring scratch) dispatches tb with
    parity vs jnp INCLUDING J — tier-1 representative at k=4."""
    tb_depth(4)
    _parity(pml=PmlConfig(size=(0, 3, 3)), depth=4, extra_state=("J",),
            materials=MaterialsConfig(
                use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                          radius=3)))


def test_tb_material_grid_parity(tb_depth):
    """ISSUE-11 acceptance: a material-grid scenario (eps sphere ->
    3D ca/cb) dispatches tb — the grids stream as per-generation tiled
    operands — with parity vs jnp; tier-1 representative at k=2."""
    tb_depth(2)
    _parity(pml=PmlConfig(size=(3, 3, 3)), depth=2,
            materials=MaterialsConfig(
                eps=2.0, eps_sphere=SphereConfig(enabled=True,
                                                 center=(8, 8, 8),
                                                 radius=4, value=6.0)))


@pytest.mark.slow
def test_tb_widened_scenarios_depth_matrix(tb_depth):
    """The full widened-scenario x depth matrix (tier-1 spreads one
    depth per scenario)."""
    for k in DEPTHS:
        tb_depth(k)
        _parity(pml=PmlConfig(size=(3, 3, 3)), depth=k,
                tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)))
        _parity(pml=PmlConfig(size=(0, 3, 3)), depth=k,
                extra_state=("J",),
                materials=MaterialsConfig(
                    use_drude=True, eps_inf=1.5, omega_p=1e11,
                    gamma=1e10,
                    drude_sphere=SphereConfig(enabled=True,
                                              center=(8, 8, 8),
                                              radius=3)))
        _parity(pml=PmlConfig(size=(3, 3, 3)), depth=k,
                materials=MaterialsConfig(
                    eps=2.0,
                    eps_sphere=SphereConfig(enabled=True,
                                            center=(8, 8, 8),
                                            radius=4, value=6.0)))


def test_tb_fallbacks_stay_on_packed():
    """Out-of-tb-scope configs must land on the round-6 packed kernel
    (never jnp, never silently tb) WITH a machine-readable
    tb_fallback reason in the step diag: in-absorber sources and
    magnetic Drude. The round-14 widened SHARDED scenarios
    (TFSF/Drude/material grids — the wedge pre-pass gained all three
    ports) now dispatch tb and are asserted in the widened sharded
    parity tests, so the dispatch can never silently regress."""
    absorber = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(2, 8, 8))))
    assert absorber.step_kind == "pallas_packed", absorber.step_kind
    assert absorber.step_diag["tb_fallback"]["reason"] == \
        "source_in_absorber"

    sharded = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(2, 2, 2)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sharded.step_kind == "pallas_packed_tb", sharded.step_kind
    assert "tb_fallback" not in (sharded.step_diag or {})

    drude_m = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        materials=MaterialsConfig(
            use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
            drude_m_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                        radius=3))))
    assert drude_m.step_kind == "pallas_packed", drude_m.step_kind
    assert drude_m.step_diag["tb_fallback"]["reason"] == \
        "magnetic_drude"


def test_tb_fallback_reason_env_and_jnp(monkeypatch):
    """Dispatch-context fallbacks are named too: the escape hatch
    records its env knob, a pallas-off run records pallas_disabled —
    the ledger and telemetry run_start carry the same record (the
    2x-HBM tax is never silent; ISSUE-14 satellite 1)."""
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed"
    assert sim.step_diag["tb_fallback"]["reason"] == \
        "env:FDTD3D_NO_TEMPORAL"
    monkeypatch.delenv("FDTD3D_NO_TEMPORAL")
    j = Simulation(SimConfig(**BASE, use_pallas=False,
                             pml=PmlConfig(size=(3, 3, 3))))
    assert j.step_kind == "jnp"
    assert j.step_diag["tb_fallback"]["reason"] == "pallas_disabled"


def test_tb_fallback_stamp_never_raises_on_unviable_pin(monkeypatch):
    """The fallback STAMP may not consult the depth picker when the
    dispatch context already declined tb: an unviable FDTD3D_TB_DEPTH
    pin combined with the escape hatch (the exact remedy the pin's
    error message recommends) or with pallas off must yield a stamped
    step, not a ValueError. The pin still raises when the dispatch
    actually consults the picker (third leg)."""
    thin = dict(BASE, pml=PmlConfig(size=(2, 0, 2)),
                parallel=ParallelConfig(topology="manual",
                                        manual_topology=(1, 8, 1)))
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "4")  # 2-cell shards: k=4
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")  # can't wedge
    s = Simulation(SimConfig(**thin, use_pallas=True))
    assert s.step_kind != "pallas_packed_tb"
    assert s.step_diag["tb_fallback"]["reason"] == \
        "env:FDTD3D_NO_TEMPORAL"
    monkeypatch.delenv("FDTD3D_NO_TEMPORAL")
    j = Simulation(SimConfig(**thin, use_pallas=False))
    assert j.step_diag["tb_fallback"]["reason"] == "pallas_disabled"
    with pytest.raises(ValueError, match="FDTD3D_TB_DEPTH=4"):
        Simulation(SimConfig(**thin, use_pallas=True))


def test_tb_plan_is_single_authority():
    """ISSUE-14 satellite 2: plan_tb is the ONE decision — the
    dispatch (make_step), the planner (plan._infer_step_kind /
    CommStrategy.ghost_depth) and the builder agree with it on
    eligibility AND depth for widened sharded configs."""
    import dataclasses as dc

    from fdtd3d_tpu import costs, solver
    from fdtd3d_tpu.ops import pallas_packed_tb
    from fdtd3d_tpu.parallel.mesh import mesh_axis_map
    from fdtd3d_tpu.plan import comm_strategy
    cfg = costs.config_tb_widened()
    topo = (2, 2, 2)
    static = dc.replace(solver.build_static(cfg), topology=topo)
    tbp = pallas_packed_tb.plan_tb(static, mesh_axis_map(topo))
    assert tbp.eligible and tbp.reason is None
    strat = comm_strategy(cfg, topo)
    assert strat.step_kind == "pallas_packed_tb"
    assert strat.ghost_depth == tbp.depth
    sim = Simulation(dc.replace(
        cfg, parallel=ParallelConfig(topology="manual",
                                     manual_topology=topo)))
    assert sim.step_kind == "pallas_packed_tb"
    assert sim.step_diag["temporal_block"] == tbp.depth


def test_tb_paired_complex_legs_stay_single_step(monkeypatch):
    """The paired-complex wrapper calls each leg once per step — a
    k-steps-per-call leg would silently multi-advance
    (make_step(allow_multistep=False))."""
    monkeypatch.setenv("FDTD3D_FORCE_PAIRED_COMPLEX", "1")
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(3, 3, 3)),
        complex_fields=True))
    assert sim.step_kind == "complex2x_pallas_packed", sim.step_kind


def test_tb_force_tile_validation():
    """make_packed_eh_step(force_tile=...) (the tb tail builder's hook)
    rejects non-divisor / too-thin tiles instead of building a
    mismatched carry layout."""
    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    assert pallas_packed.make_packed_eh_step(static, force_tile=5) is None
    assert pallas_packed.make_packed_eh_step(static, force_tile=16) is None
    ok = pallas_packed.make_packed_eh_step(static, force_tile=8)
    assert ok is not None and ok.diag["tile"]["EH"] == 8


def test_tb_step_contract(tb_depth):
    """The multi-step step object's contract with make_chunk_runner:
    steps_per_call == the pipeline depth k, a single-step tail at the
    SAME tile, shared pack/unpack/prepare."""
    from fdtd3d_tpu import solver
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    static = solver.build_static(cfg)
    tb_depth(3)
    step = solver.make_step(static)
    assert step.kind == "pallas_packed_tb"
    assert step.steps_per_call == 3
    assert step.diag["temporal_block"] == 3
    tail = step.tail_step
    assert tail.kind == "pallas_packed"
    assert tail.diag["tile"]["EH"] == step.diag["tile"]["EH"]
    assert step.pack is tail.pack and step.unpack is tail.unpack
    assert step.prepare is tail.prepare
    # the one-step contract escape for wrappers
    single = solver.make_step(static, allow_multistep=False)
    assert single.kind == "pallas_packed"
    # a chunk runner built on the tb step reports the multi-step shape
    runner = solver.make_chunk_runner(static)
    assert runner.kind == "pallas_packed_tb"
    assert runner.steps_per_call == 3


# -------------------------------------------------------------------------
# donation safety (structural, mirrors test_h_inputs_never_donated)
# -------------------------------------------------------------------------

def test_tb_donation_fetch_before_write(monkeypatch):
    """Structural donation-safety AT EVERY DEPTH: every ALIASED
    operand's in-map must be monotone (each HBM block fetched once)
    and fetch each block no later than the out-map's first visit of it
    — backward-read state never sees a block its own (masked or real)
    output writes could already have flushed. Non-field operands
    (profiles, source, walls, TFSF planes) must not be donated at all.
    Interpreter mode cannot surface the hazard at runtime — assert the
    structure."""
    from jax.experimental import pallas as pl

    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_packed_tb

    captured = {}
    real_call = pl.pallas_call

    def spy(kernel, **kw):
        captured["aliases"] = dict(kw.get("input_output_aliases") or {})
        captured["in_specs"] = list(kw.get("in_specs"))
        captured["out_specs"] = list(kw.get("out_specs"))
        captured["grid"] = kw.get("grid")
        return real_call(kernel, **kw)

    monkeypatch.setattr(pallas_packed_tb.pl, "pallas_call", spy)
    cfg = SimConfig(**dict(BASE, size=(48, 16, 16)), use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez",
                        position=(24, 8, 8)))
    static = solver.build_static(cfg)
    for depth in DEPTHS:
        captured.clear()
        step = pallas_packed_tb.make_packed_tb_step(static, depth=depth)
        assert step is not None and captured, depth

        aliases = captured["aliases"]
        n_in = len(captured["in_specs"])
        n_out = len(captured["out_specs"])
        # every output is fed by a donated input with the same
        # position; everything else (profiles/source/walls) is NOT
        # donated
        assert aliases == {j: j for j in range(n_out)}, (depth, aliases)
        assert n_in > n_out

        (n_iters,) = captured["grid"]

        def blocks(spec):
            # x-block index per grid iteration (index maps are pure)
            return [int(spec.index_map(i)[1]) for i in range(n_iters)]

        for j in sorted(aliases):
            fetches = blocks(captured["in_specs"][j])
            visits = blocks(captured["out_specs"][aliases[j]])
            assert fetches == sorted(fetches), \
                f"k={depth} operand {j}: non-monotone in-map {fetches}"
            first_fetch = {}
            for i, b in enumerate(fetches):
                first_fetch.setdefault(b, i)
            first_visit = {}
            for i, b in enumerate(visits):
                first_visit.setdefault(b, i)
            for b, fi in first_fetch.items():
                assert fi <= first_visit.get(b, n_iters), (
                    f"k={depth} operand {j}: block {b} fetched at "
                    f"iteration {fi} after its first out visit "
                    f"{first_visit.get(b)} — donation hazard")


# -------------------------------------------------------------------------
# chunk runner / carry / flight recorder integration
# -------------------------------------------------------------------------

def test_tb_multi_chunk_odd_chunks_carry(tb_depth):
    """Chunk lengths not divisible by k run blocked passes + the
    single-step tail INSIDE one compiled chunk; several such chunks
    must compose to the same answer as one scan (k=3: 6 = 2 blocked,
    3 = 1 blocked + 1 tail)."""
    tb_depth(3)
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    one = Simulation(cfg)
    one.advance(6)
    many = Simulation(cfg)
    many.advance(3)   # 1 blocked + 1 tail
    _ = many.state["E"]["Ez"]      # force an unpack between chunks
    many.advance(3)   # again (re-uses the compiled length)
    assert many.step_kind == "pallas_packed_tb"
    assert many.step_diag["temporal_block"] == 3
    assert one.t == many.t == 6
    a = np.asarray(one.field("Ez"))
    b = np.asarray(many.field("Ez"))
    assert np.abs(a - b).max() / (np.abs(a).max() + 1e-30) < 2e-6


def test_tb_checkpoint_resume_mid_blocked_chunk(tb_depth):
    """Bit-exact resume from a snapshot taken at a step count that is
    NOT a multiple of k (t=4 at k=3: the chunk before it ran 1 blocked
    pass + 1 tail) — the packed carry, Drude-free psi state and the
    t mirror all restore onto the identical layout."""
    tb_depth(3)
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)))
    import tempfile
    sim = Simulation(cfg)
    sim.advance(4)   # 1 blocked + 1 tail: mid-blocked-chunk t
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.npz")
        sim.checkpoint(path)
        sim.advance(4)
        ref = np.asarray(sim.field("Ez"))
        res = Simulation(cfg)
        res.restore(path)
        assert res.t == 4
        res.advance(4)
        got = np.asarray(res.field("Ez"))
    assert np.abs(ref - got).max() == 0.0   # bit-exact resume


def test_tb_health_counters_unpack_blocked_carry(tmp_path):
    """The flight recorder's in-graph health counters must unpack the
    tb packed carry (telemetry satellite): finite energy per chunk,
    matching the jnp run's counters, non-divisible chunk included;
    run_start records the ghost_depth the step consumed."""
    from fdtd3d_tpu import telemetry

    def run(up):
        cfg = SimConfig(
            **BASE, use_pallas=up, pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(8, 8, 8)),
            output=OutputConfig(
                telemetry_path=str(tmp_path / f"t_{up}.jsonl"),
                check_finite=True))
        sim = Simulation(cfg)
        sim.advance(5)   # non-divisible: blocked passes + tail(s)
        sim.close_telemetry()
        return sim, telemetry.read_jsonl(cfg.output.telemetry_path)

    sim_p, recs_p = run(True)
    assert sim_p.step_kind == "pallas_packed_tb"
    start = [r for r in recs_p if r["type"] == "run_start"][0]
    assert start["ghost_depth"] == sim_p.step_diag["temporal_block"]
    sim_j, recs_j = run(False)
    starts_j = [r for r in recs_j if r["type"] == "run_start"]
    assert "ghost_depth" not in starts_j[0]   # single-step kind: absent
    chunks_p = [r for r in recs_p if r["type"] == "chunk"]
    chunks_j = [r for r in recs_j if r["type"] == "chunk"]
    assert [c["t"] for c in chunks_p] == [5]
    for cp, cj in zip(chunks_p, chunks_j):
        assert cp["finite"] is True
        assert cp["energy"] == pytest.approx(cj["energy"], rel=1e-4)
        assert cp["max_e"] == pytest.approx(cj["max_e"], rel=1e-4)


def test_tb_vmem_ladder_depth_downgrade(monkeypatch):
    """A VMEM-ladder rebuild that lands on a SHALLOWER pipeline depth
    (k -> k-1) is SOUND (same packed-carry family, re-packed through
    the dict form), keeps the run alive, and emits the ghost_depth
    pair on the ladder_downgrade event."""
    from fdtd3d_tpu import solver, telemetry

    monkeypatch.setenv("FDTD3D_TB_DEPTH", "4")
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    sim = Simulation(cfg)
    assert sim.step_diag["temporal_block"] == 4
    _seed_fields(sim, seed=3)
    sim.advance(4)   # materialize the packed carry

    real = solver.make_chunk_runner

    def forced_k3(static, mesh_axes=None, mesh_shape=None,
                  health=False, per_chip=False):
        saved = os.environ.get("FDTD3D_TB_DEPTH")
        os.environ["FDTD3D_TB_DEPTH"] = "3"
        try:
            return real(static, mesh_axes, mesh_shape, health=health,
                        per_chip=per_chip)
        finally:
            os.environ["FDTD3D_TB_DEPTH"] = saved

    events = []
    monkeypatch.setattr(solver, "make_chunk_runner", forced_k3)
    sim.telemetry = type("Sink", (), {
        "emit": lambda self, typ, **kw: events.append((typ, kw)),
    })()
    sim._vmem_fallback(RuntimeError("mosaic vmem overflow (simulated)"))
    sim.telemetry = None
    assert sim.step_kind == "pallas_packed_tb"
    assert sim.step_diag["temporal_block"] == 3
    assert events and events[0][0] == "ladder_downgrade"
    assert events[0][1]["old_ghost_depth"] == 4
    assert events[0][1]["new_ghost_depth"] == 3
    assert "old_ghost_depth" in telemetry.RECORD_OPTIONAL[
        "ladder_downgrade"]
    sim.advance(4)

    ref = Simulation(SimConfig(**dict(BASE, use_pallas=False,
                                      pml=PmlConfig(size=(3, 3, 3)))))
    _seed_fields(ref, seed=3)
    ref.advance(8)
    for c in ("Ez", "Hy"):
        a = np.asarray(ref.field(c), np.float32)
        b = np.asarray(sim.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


def test_tb_vmem_ladder_downgrade_to_packed(monkeypatch):
    """The bottom of the depth ladder: a rebuild that falls out of tb
    scope entirely down to the single-step packed kernel is SOUND
    (same packed-carry family, re-packed through the dict form) and
    must keep the run alive."""
    from fdtd3d_tpu import solver
    cfg = SimConfig(**BASE, use_pallas=True,
                    pml=PmlConfig(size=(3, 3, 3)))
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_tb"
    _seed_fields(sim, seed=3)
    sim.advance(2)   # materialize the packed carry

    real = solver.make_chunk_runner

    def forced_packed(static, mesh_axes=None, mesh_shape=None,
                      health=False, per_chip=False):
        saved = os.environ.get("FDTD3D_NO_TEMPORAL")
        os.environ["FDTD3D_NO_TEMPORAL"] = "1"
        try:
            return real(static, mesh_axes, mesh_shape, health=health,
                        per_chip=per_chip)
        finally:
            if saved is None:
                os.environ.pop("FDTD3D_NO_TEMPORAL", None)
            else:
                os.environ["FDTD3D_NO_TEMPORAL"] = saved

    monkeypatch.setattr(solver, "make_chunk_runner", forced_packed)
    sim.step_diag = dict(sim.step_diag, tile={"EH": 99})
    sim._vmem_fallback(RuntimeError("mosaic vmem overflow (simulated)"))
    assert sim.step_kind == "pallas_packed"
    sim.advance(6)

    ref = Simulation(cfg.__class__(**dict(BASE, use_pallas=False,
                                          pml=PmlConfig(size=(3, 3, 3)))))
    _seed_fields(ref, seed=3)
    ref.advance(8)
    for c in ("Ez", "Hy"):
        a = np.asarray(ref.field(c), np.float32)
        b = np.asarray(sim.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"
