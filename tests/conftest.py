"""Test env: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's oversubscribed single-host `mpirun -n N` unit-test
pattern for ParallelGrid (SURVEY.md §4) with XLA's host-platform device
count, per the driver's instructions.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's TPU plugin overrides JAX_PLATFORMS at registration, so
# pin the platform through the config API too (verified: env var alone
# still yields the TPU; config.update yields the 8 virtual CPU devices).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the float32x2 step's EFT graph is
# ~11k HLO ops and XLA:CPU takes minutes to compile it; caching makes
# repeat test runs (and reruns within CI) skip that cost.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/jax_fdtd3d_tests"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
