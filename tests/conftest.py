"""Test env: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's oversubscribed single-host `mpirun -n N` unit-test
pattern for ParallelGrid (SURVEY.md §4) with XLA's host-platform device
count, per the driver's instructions.
"""

import os

# FDTD3D_TEST_TPU=1 skips the CPU pin so the suite (incl. the
# chip-lane-only tests, e.g. test_packed_ds_point_source_parity) runs
# against the real TPU backend; default is the 8-device virtual CPU
# mesh below.
_force_tpu = bool(os.environ.get("FDTD3D_TEST_TPU"))
if not _force_tpu:
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's TPU plugin overrides JAX_PLATFORMS at registration, so
# pin the platform through the config API too (verified: env var alone
# still yields the TPU; config.update yields the 8 virtual CPU devices).
import jax  # noqa: E402

if not _force_tpu:
    jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the float32x2 step's EFT graph is
# ~11k HLO ops and XLA:CPU takes minutes to compile it; caching makes
# repeat test runs (and reruns within CI) skip that cost.
#
# Round-6 caveat this cache depends on: CACHE-DESERIALIZED XLA:CPU
# executables with DONATED buffers mis-execute on this jax build,
# writing into buffers other live arrays occupy (reproduced as
# nondeterministic corruption of a previously-run sim's fields, on the
# unmodified round-5 kernels too; always clean when either the cache
# or donation is off). Simulation therefore donates the scan carry on
# TPU backends only (sim._chunk_fn) — if donation is ever re-enabled
# on CPU, this cache must go.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/jax_fdtd3d_tests"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
