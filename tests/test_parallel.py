"""Domain-decomposition tests on the 8-device virtual CPU mesh.

The ParallelGrid/BufferShare test analog (SURVEY.md §4: the reference runs
unit-test-parallel-grid under oversubscribed `mpirun -n N` for each
buffer-dimension mode). Here: every decomposition topology the reference
supports (x, y, z, xy, yz, xz, xyz — SURVEY.md §2.9) must produce fields
IDENTICAL (up to f32 roundoff) to the unsharded run, with the full physics
stack active (CPML + TFSF + Drude) so every ppermute halo path is hit.
"""

import numpy as np
import pytest

import jax

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.parallel.mesh import choose_topology
from fdtd3d_tpu.sim import Simulation

TOPOLOGIES = [
    (2, 1, 1), (1, 2, 1), (1, 1, 2),          # 1-axis (x | y | z)
    (2, 2, 1), (1, 2, 2), (2, 1, 2),          # 2-axis (xy | yz | xz)
    (2, 2, 2),                                # 3-axis (xyz)
    (4, 2, 1),                                # uneven 2-axis
]


def _full_physics_cfg(parallel=None):
    n = 16
    return SimConfig(
        scheme="3D", size=(n, n, n), time_steps=12, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                        angle_teta=30.0, angle_phi=40.0, angle_psi=15.0),
        materials=MaterialsConfig(
            eps=1.0, use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True,
                                      center=(8.0, 8.0, 8.0), radius=3.0),
            use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
            drude_m_sphere=SphereConfig(enabled=True,
                                       center=(8.0, 8.0, 8.0),
                                       radius=3.0)),
        parallel=parallel or ParallelConfig(),
    )


def test_mesh_has_8_devices():
    assert jax.device_count() == 8, (
        "conftest must provision 8 virtual CPU devices BEFORE jax init")


@pytest.fixture(scope="module")
def reference_fields():
    sim = Simulation(_full_physics_cfg())
    sim.run()
    return sim.fields()


@pytest.mark.parametrize("topo", TOPOLOGIES)
def test_sharded_matches_unsharded(topo, reference_fields):
    cfg = _full_physics_cfg(ParallelConfig(topology="manual",
                                           manual_topology=topo))
    sim = Simulation(cfg)
    assert sim.mesh is not None, "sharded path not engaged"
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        err = np.abs(got[comp] - ref).max()
        assert err < 1e-5 * scale, f"{comp}: {err/scale:.2e} on {topo}"


def test_auto_topology_runs():
    cfg = _full_physics_cfg(ParallelConfig(topology="auto", n_devices=8))
    sim = Simulation(cfg)
    assert sim.mesh is not None
    assert int(np.prod(sim.topology)) == 8
    sim.run()
    for comp, v in sim.fields().items():
        assert np.isfinite(v).all()


def test_2d_decomposition():
    """2D TMz sharded over xy must match unsharded."""
    n = 32
    def cfg(par=None):
        return SimConfig(
            scheme="2D_TMz", size=(n, n, 1), time_steps=20, dx=1e-3,
            courant_factor=0.5, wavelength=10e-3,
            pml=PmlConfig(size=(4, 4, 0)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(n // 2, n // 2, 0)),
            parallel=par or ParallelConfig())
    ref = Simulation(cfg()); ref.run()
    shd = Simulation(cfg(ParallelConfig(topology="manual",
                                        manual_topology=(4, 2, 1))))
    shd.run()
    for comp, r in ref.fields().items():
        scale = np.abs(r).max() + 1e-30
        assert np.abs(shd.fields()[comp] - r).max() < 1e-5 * scale


# ---- topology chooser unit tests (reference auto-topology analog) -------

def test_choose_topology_prefers_single_long_axis():
    # 256x64x64: all 8 cuts along x minimize the exchanged plane area.
    assert choose_topology(8, (256, 64, 64), (0, 1, 2)) == (8, 1, 1)


def test_choose_topology_cube_prefers_3d_blocks():
    # cube: (2,2,2) has less per-device halo than (8,1,1) slabs.
    topo = choose_topology(8, (64, 64, 64), (0, 1, 2))
    assert sorted(topo) == [2, 2, 2]


def test_choose_topology_respects_divisibility():
    # 96 divides by 3; 64 doesn't: 3 must land on axis 0.
    topo = choose_topology(3, (96, 64, 64), (0, 1, 2))
    assert topo == (3, 1, 1)


def test_choose_topology_inactive_axes_never_sharded():
    topo = choose_topology(4, (64, 64, 1), (0, 1))
    assert topo[2] == 1
