"""REAL multi-process runs: the reference's `mpirun -n N` test analog.

Two OS processes (2 virtual CPU devices each) join over the JAX
distributed runtime (gloo), build one 4-device mesh spanning both
processes, run the full-physics solve, and allgather the result — which
must match the single-process reference bit-for-bit-close. This is the
closest in-environment equivalent of the reference's multi-node MPI
path (DCN collectives between hosts).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = r"""
import json, os, sys
pid, nproc, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                            int(sys.argv[3]), sys.argv[4])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from fdtd3d_tpu.parallel import distributed
distributed.initialize(coordinator=f"127.0.0.1:{port}",
                       num_processes=nproc, process_id=pid)
assert jax.device_count() == 2 * nproc
assert jax.process_count() == nproc

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               SimConfig, SphereConfig, TfsfConfig)
from fdtd3d_tpu.sim import Simulation
cfg = SimConfig(
    scheme="3D", size=(16, 16, 16), time_steps=10, dx=1e-3,
    courant_factor=0.4, wavelength=8e-3,
    pml=PmlConfig(size=(3, 3, 3)),
    tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                    angle_teta=30.0, angle_phi=40.0, angle_psi=15.0),
    materials=MaterialsConfig(
        use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
        drude_sphere=SphereConfig(enabled=True, center=(8.0, 8.0, 8.0),
                                  radius=3.0)),
    parallel=ParallelConfig(topology="auto"))
sim = Simulation(cfg)
assert sim.mesh is not None and sim.mesh.devices.size == 2 * nproc
# NTFF sampling + device-side metrics are COLLECTIVE (every rank calls
# them) and must work in multi-process runs (VERDICT r2 item 5).
from fdtd3d_tpu import diag
from fdtd3d_tpu.ntff import NtffCollector
from fdtd3d_tpu import physics
col = NtffCollector(sim, frequency=physics.C0 / cfg.wavelength, margin=0)
sim.run(on_interval=lambda s: col.sample(), interval=2)
met = diag.metrics(sim)
et, ep = col.far_field(90.0, 0.0)
ez = sim.field("Ez")   # allgathered: full global array on every process
import numpy as np
np.save(os.path.join(outdir, f"ez_{pid}.npy"), np.asarray(ez))
np.save(os.path.join(outdir, f"ntff_{pid}.npy"),
        np.array([et, ep], dtype=np.complex128))
with open(os.path.join(outdir, f"metrics_{pid}.json"), "w") as f:
    json.dump(met, f)
print("WORKER_OK", pid)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Capability probe: some jax builds cannot COMPILE a computation that
# spans processes on the CPU backend at all ("Multiprocess computations
# aren't implemented on the CPU backend" — the distributed runtime
# initializes fine, the first process-spanning executable dies). That
# is an environment limit, not a repo bug (the seed fails identically),
# so the real test below skips with the probe's reason instead of
# carrying a permanent red. Any OTHER probe failure lets the real test
# run and report properly.
_MP_PROBE = r"""
import os, sys
pid, port = int(sys.argv[1]), int(sys.argv[2])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from fdtd3d_tpu.parallel import distributed
distributed.initialize(coordinator=f"127.0.0.1:{port}",
                       num_processes=2, process_id=pid)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("d",))
sh = NamedSharding(mesh, P("d"))
x = jax.device_put(np.arange(2, dtype=np.float32), sh)
y = jax.jit(lambda v: v * 2, out_shardings=sh)(x)
jax.block_until_ready(y)
print("MP_PROBE_OK", pid)
"""

_MP_SUPPORT = None  # (ok, reason), probed once per session


def _multiprocess_cpu_support():
    global _MP_SUPPORT
    if _MP_SUPPORT is not None:
        return _MP_SUPPORT
    import tempfile
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("JAX_PLATFORMS", None)
    with tempfile.TemporaryDirectory() as td:
        probe = os.path.join(td, "probe.py")
        with open(probe, "w") as f:
            f.write(_MP_PROBE)
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, probe, str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    combined = "\n".join(outs)
    if "aren't implemented on the CPU backend" in combined:
        _MP_SUPPORT = (False,
                       "this jax cannot compile multiprocess "
                       "computations on the CPU backend "
                       "(XlaRuntimeError INVALID_ARGUMENT; probed, "
                       "fails identically at the repo seed)")
    else:
        # healthy, or an unrecognized failure the real test must report
        _MP_SUPPORT = (True, "")
    return _MP_SUPPORT


def test_two_process_run_matches_single_process(tmp_path):
    ok, reason = _multiprocess_cpu_support()
    if not ok:
        pytest.skip(reason)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ,
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("JAX_PLATFORMS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), "2", str(port),
         str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK {pid}" in out

    ez0 = np.load(tmp_path / "ez_0.npy")
    ez1 = np.load(tmp_path / "ez_1.npy")
    assert np.array_equal(ez0, ez1), "processes disagree on the result"

    # single-process reference on the same config (8-device mesh differs
    # in topology, so compare against an UNSHARDED run)
    from fdtd3d_tpu.config import (MaterialsConfig, PmlConfig, SimConfig,
                                   SphereConfig, TfsfConfig)
    from fdtd3d_tpu.sim import Simulation
    cfg = SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=10, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                        angle_teta=30.0, angle_phi=40.0, angle_psi=15.0),
        materials=MaterialsConfig(
            use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True,
                                      center=(8.0, 8.0, 8.0), radius=3.0)))
    from fdtd3d_tpu import diag, physics
    from fdtd3d_tpu.ntff import NtffCollector
    ref = Simulation(cfg)
    col = NtffCollector(ref, frequency=physics.C0 / cfg.wavelength,
                        margin=0)
    ref.run(on_interval=lambda s: col.sample(), interval=2)
    r = ref.field("Ez")
    scale = np.abs(r).max() + 1e-30
    assert np.abs(ez0 - r).max() < 1e-5 * scale

    # multi-process NTFF + collective metrics match the unsharded run
    nt0 = np.load(tmp_path / "ntff_0.npy")
    nt1 = np.load(tmp_path / "ntff_1.npy")
    assert np.allclose(nt0, nt1), "ranks disagree on the far field"
    et, ep = col.far_field(90.0, 0.0)
    ref_ff = np.array([et, ep])
    ff_scale = np.abs(ref_ff).max() + 1e-30
    assert np.abs(nt0 - ref_ff).max() < 1e-4 * ff_scale
    met0 = json.loads((tmp_path / "metrics_0.json").read_text())
    ref_met = diag.metrics(ref)
    for k in ("energy", "max_Ez", "div_l2"):
        assert met0[k] == pytest.approx(ref_met[k], rel=1e-4, abs=1e-30)
