"""fdtd3d_tpu/tail.py: incremental JSONL tailing with durable cursors.

The properties under test are exactly the ones the fleet watcher and
``fleet_report --follow`` lean on:

* INCREMENTAL — a poll costs the bytes appended since the last poll,
  not the file size (``bytes_read`` is the proof surface).
* CARRY — a partial trailing line is held back, not parsed, and
  completes on the next poll.
* NAMED FAILURE — rotation (inode change) and truncation (size under
  cursor) reset to zero AND leave an explanatory event; they never
  silently double-count or drop.
* DURABLE — ``checkpoint()`` + a fresh Tailer on the same cursor path
  resumes at the committed offset.
"""

import json
import os

import pytest

from fdtd3d_tpu import tail


def _append(path, text):
    with open(path, "a") as fh:
        fh.write(text)


# ---------------------------------------------------------------------------
# incrementality
# ---------------------------------------------------------------------------

def test_poll_is_incremental_bytes_do_not_rescale(tmp_path):
    """Growing the file does NOT grow the cost of polling the delta:
    after a large prefix is consumed once, a small append costs only
    its own bytes."""
    p = str(tmp_path / "stream.jsonl")
    big = "".join(json.dumps({"type": "chunk", "i": i}) + "\n"
                  for i in range(500))
    _append(p, big)
    t = tail.Tailer()
    assert len(t.poll(p)) == 500
    cost_prefix = t.bytes_read
    assert cost_prefix == len(big)

    small = json.dumps({"type": "chunk", "i": 500}) + "\n"
    _append(p, small)
    assert len(t.poll(p)) == 1
    assert t.bytes_read - cost_prefix == len(small)

    # an empty poll costs nothing at all
    before = t.bytes_read
    assert t.poll(p) == []
    assert t.bytes_read == before


def test_poll_missing_file_is_empty_not_error(tmp_path):
    t = tail.Tailer()
    assert t.poll(str(tmp_path / "nope.jsonl")) == []
    assert t.bytes_read == 0
    assert t.events == []


# ---------------------------------------------------------------------------
# partial-line carry
# ---------------------------------------------------------------------------

def test_partial_line_carried_until_complete(tmp_path):
    p = str(tmp_path / "s.jsonl")
    t = tail.Tailer()
    _append(p, '{"a": 1}\n{"a": ')
    assert t.poll(p) == ['{"a": 1}']
    _append(p, '2}\n')
    assert t.poll_records(p) == [{"a": 2}]
    assert t.events == []


# ---------------------------------------------------------------------------
# rotation / truncation are named, not absorbed
# ---------------------------------------------------------------------------

def test_truncation_resets_and_names_itself(tmp_path):
    p = str(tmp_path / "s.jsonl")
    t = tail.Tailer()
    _append(p, '{"a": 1}\n{"a": 2}\n')
    assert len(t.poll(p)) == 2
    with open(p, "w") as fh:  # rewrite shorter in place
        fh.write('{"a": 3}\n')
    assert t.poll_records(p) == [{"a": 3}]
    evts = t.drain_events()
    assert len(evts) == 1 and evts[0].startswith("truncated:")
    assert t.drain_events() == []  # drain clears


def test_rotation_resets_and_names_itself(tmp_path):
    p = str(tmp_path / "s.jsonl")
    t = tail.Tailer()
    _append(p, '{"a": 1}\n')
    assert len(t.poll(p)) == 1
    os.rename(p, p + ".1")  # classic copy-then-recreate rotation
    _append(p, '{"a": 2}\n')
    assert t.poll_records(p) == [{"a": 2}]
    evts = t.drain_events()
    assert len(evts) == 1 and evts[0].startswith("rotated:")


# ---------------------------------------------------------------------------
# tolerant vs strict record parsing
# ---------------------------------------------------------------------------

def test_poll_records_tolerant_skips_and_names_bad_lines(tmp_path):
    p = str(tmp_path / "s.jsonl")
    _append(p, '{"ok": 1}\nnot json at all\n[1, 2]\n{"ok": 2}\n')
    t = tail.Tailer()
    assert t.poll_records(p) == [{"ok": 1}, {"ok": 2}]
    evts = t.drain_events()
    assert any("unparseable" in e for e in evts)
    assert any("non-object" in e for e in evts)


def test_poll_records_strict_raises(tmp_path):
    p = str(tmp_path / "s.jsonl")
    _append(p, 'garbage\n')
    with pytest.raises(ValueError, match="unparseable"):
        tail.Tailer().poll_records(p, strict=True)


# ---------------------------------------------------------------------------
# durable checkpoint / resume
# ---------------------------------------------------------------------------

def test_checkpoint_resume_skips_consumed_history(tmp_path):
    p = str(tmp_path / "s.jsonl")
    cur = str(tmp_path / "cursor.json")
    big = "".join(json.dumps({"i": i}) + "\n" for i in range(200))
    _append(p, big)

    t1 = tail.Tailer(cursor_path=cur)
    assert len(t1.poll(p)) == 200
    t1.checkpoint()

    # a restarted tailer resumes at the committed offset: history is
    # NOT re-read (bytes_read counts only the fresh delta)
    _append(p, '{"i": 200}\n')
    t2 = tail.Tailer(cursor_path=cur)
    assert t2.poll_records(p) == [{"i": 200}]
    assert t2.bytes_read == len('{"i": 200}\n')


def test_checkpoint_preserves_carry(tmp_path):
    p = str(tmp_path / "s.jsonl")
    cur = str(tmp_path / "cursor.json")
    _append(p, '{"a": 1}\n{"a": ')
    t1 = tail.Tailer(cursor_path=cur)
    assert len(t1.poll(p)) == 1
    t1.checkpoint()

    _append(p, '2}\n')
    t2 = tail.Tailer(cursor_path=cur)
    assert t2.poll_records(p) == [{"a": 2}]


def test_bad_cursor_file_starts_from_zero_with_event(tmp_path):
    p = str(tmp_path / "s.jsonl")
    cur = str(tmp_path / "cursor.json")
    _append(p, '{"a": 1}\n')
    with open(cur, "w") as fh:
        fh.write("{broken")
    t = tail.Tailer(cursor_path=cur)
    assert any("unreadable" in e for e in t.drain_events())
    assert t.poll_records(p) == [{"a": 1}]


def test_version_mismatch_cursor_starts_from_zero(tmp_path):
    p = str(tmp_path / "s.jsonl")
    cur = str(tmp_path / "cursor.json")
    _append(p, '{"a": 1}\n')
    with open(cur, "w") as fh:
        json.dump({"version": 99, "files": {p: {"offset": 9}}}, fh)
    t = tail.Tailer(cursor_path=cur)
    assert any("version" in e for e in t.drain_events())
    assert t.poll_records(p) == [{"a": 1}]
