"""Coordinated multi-writer commit tests (ISSUE 8 tentpole piece 2).

Two-phase marker protocol (io.py): each participating process
atomically publishes its shards plus a per-host marker (phase 1);
process 0 publishes COMMIT.fdtd3d only after observing the FULL marker
set (phase 2). Discovery treats any partial marker set as uncommitted
— skipped with a warning, never a crash.

Proven CPU-deterministically with SIMULATED writer sets
(faults.simulated_host drives the protocol once per host) plus
fault-plan kill points between the phases (host_lost, host-scoped
fail_write).
"""

import json
import os

import numpy as np
import pytest

from fdtd3d_tpu import faults, io


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _publish_all(dirpath, hosts, num_writers):
    """Simulate each writer's phase 1: shard payload + host marker."""
    os.makedirs(dirpath, exist_ok=True)
    for h in hosts:
        with faults.simulated_host(h):
            # the "shard": any payload the writer owns, atomically
            io.save_checkpoint({"E": {"Ez": np.full((4, 4), h, np.float32)}},
                               os.path.join(dirpath, f"shard_{h:04d}.npz"),
                               extra={"host": h})
            io.publish_host_marker(dirpath, h, num_writers)


def test_two_phase_commit_happy_path(tmp_path):
    d = str(tmp_path / "ckpt_t000008")
    _publish_all(d, [0, 1, 2], 3)
    st = io.commit_status(d)
    assert st["markers"] == [0, 1, 2] and st["missing"] == []
    assert not st["committed"]       # phase 2 has not run yet
    assert io.commit_if_complete(d, 3) is True
    st = io.commit_status(d)
    assert st["committed"] and not st["legacy"]
    # the COMMIT marker records the writer set
    with open(os.path.join(d, io.ORBAX_COMMIT_MARKER)) as f:
        commit = json.load(f)
    assert commit == {"num_writers": 3, "hosts": [0, 1, 2]}
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [8]


def test_partial_marker_set_never_commits(tmp_path, capsys):
    d = str(tmp_path / "ckpt_t000008")
    _publish_all(d, [0, 2], 3)       # host 1 never published
    assert io.commit_if_complete(d, 3) is False
    assert not os.path.exists(os.path.join(d, io.ORBAX_COMMIT_MARKER))
    st = io.commit_status(d)
    assert not st["committed"] and st["missing"] == [1]
    # discovery: skipped WITH a warning naming the lost writer
    assert io.find_checkpoints(str(tmp_path)) == []
    err = capsys.readouterr().err
    assert "partial commit-marker set" in err and "[1]" in err
    # and the metadata reader refuses it with the named failure
    with pytest.raises(io.CheckpointCorrupt, match=r"hosts \[1\] of 3"):
        io.read_orbax_meta(d)


def test_commit_over_partial_set_does_not_count(tmp_path):
    """A hand-rolled/damaged COMMIT over an incomplete marker set must
    not resurrect the snapshot: the partial set is authoritative."""
    d = str(tmp_path / "ckpt_t000008")
    _publish_all(d, [0], 2)
    with io.atomic_open(os.path.join(d, io.ORBAX_COMMIT_MARKER)) as f:
        f.write("forged\n")
    assert not io.commit_status(d)["committed"]
    assert io.find_checkpoints(str(tmp_path)) == []


def test_stray_marker_never_enables_or_poisons_commit(tmp_path):
    """A stray marker from an earlier crashed WIDER writer set must
    neither stand in for a missing real writer (phase 2 requires
    set(range(n)) <= present, not a subset test the stray can tilt)
    nor poison a complete smaller set on the read side (the COMMIT
    marker's recorded writer count is authoritative)."""
    d = str(tmp_path / "ckpt_t000008")
    _publish_all(d, [0], 2)
    with faults.simulated_host(3):
        io.publish_host_marker(d, 3, 4)   # leftover of a 4-writer era
    # host 1 missing: the stray must NOT complete the set
    assert io.commit_if_complete(d, 2) is False
    assert io.find_checkpoints(str(tmp_path)) == []
    # once host 1 publishes, the commit goes through, and readers
    # honor the COMMIT's num_writers=2 despite the stray claiming 4
    _publish_all(d, [1], 2)
    assert io.commit_if_complete(d, 2) is True
    st = io.commit_status(d)
    assert st["committed"] and st["num_writers"] == 2
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [8]


def test_legacy_single_writer_dir_still_committed(tmp_path):
    """Pre-two-phase directories (COMMIT marker, no host markers) keep
    reading as committed — old snapshots must not rot."""
    d = str(tmp_path / "ckpt_t000016")
    os.makedirs(d)
    with io.atomic_open(os.path.join(d, io.ORBAX_COMMIT_MARKER)) as f:
        f.write("committed\n")
    st = io.commit_status(d)
    assert st["committed"] and st["legacy"]
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [16]


# -------------------------------------------------------------------------
# kill points between the phases (faults.py)
# -------------------------------------------------------------------------

def test_host_lost_between_phases_leaves_partial_set(tmp_path):
    """host_lost@n=H kills exactly writer H before its marker lands;
    the set stays partial, the commit never happens, and — the fault
    being one-shot — the writer's RETRY completes the snapshot."""
    d = str(tmp_path / "ckpt_t000008")
    faults.install("host_lost@n=1")
    with pytest.raises(faults.SimulatedHostLoss):
        _publish_all(d, [0, 1, 2], 3)
    # hosts 0 published; 1 died; 2 never ran (ordered simulation)
    assert io.commit_if_complete(d, 3) is False
    assert io.find_checkpoints(str(tmp_path)) == []
    # the incident is one-shot: the resumed writers complete phase 1
    _publish_all(d, [1, 2], 3)
    assert io.commit_if_complete(d, 3) is True
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [8]


def test_host_lost_is_never_swallowed():
    assert issubclass(faults.SimulatedHostLoss,
                      faults.SimulatedPreemption)
    assert not issubclass(faults.SimulatedHostLoss, Exception)


def test_host_scoped_fail_write(tmp_path):
    """fail_write@n=1,host=1 fails host 1's FIRST write only — other
    writers' counters are untouched, and the atomic contract holds
    (no marker debris under the final name)."""
    d = str(tmp_path / "ckpt_t000008")
    faults.install("fail_write@n=1,host=1")
    with faults.simulated_host(0):
        io.publish_host_marker(d, 0, 3)      # host 0 write #1: fine
    with faults.simulated_host(1):
        with pytest.raises(faults.InjectedWriteError, match="host 1"):
            io.publish_host_marker(d, 1, 3)  # host 1 write #1: fails
    with faults.simulated_host(2):
        io.publish_host_marker(d, 2, 3)
    st = io.commit_status(d)
    assert st["markers"] == [0, 2] and st["missing"] == [1]
    assert not any(".tmp." in n for n in os.listdir(d))
    # one-shot: host 1's retry lands, commit completes
    with faults.simulated_host(1):
        io.publish_host_marker(d, 1, 3)
    assert io.commit_if_complete(d, 3) is True


def test_simulated_host_scopes_current_host():
    assert faults.current_host() == 0     # single-process default
    with faults.simulated_host(5):
        assert faults.current_host() == 5
        with faults.simulated_host(2):
            assert faults.current_host() == 2
        assert faults.current_host() == 5
    assert faults.current_host() == 0


# -------------------------------------------------------------------------
# the real sharded saver rides the same protocol
# -------------------------------------------------------------------------

def test_orbax_save_publishes_markers_and_commit(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    import jax.numpy as jnp
    d = str(tmp_path / "ckpt_t000004")
    io.save_checkpoint_orbax({"E": {"Ez": jnp.zeros((8, 8))}}, d,
                             extra={"t": 4})
    assert os.path.exists(os.path.join(d, io.host_marker_name(0)))
    st = io.commit_status(d)
    assert st["committed"] and st["num_writers"] == 1
    assert io.read_orbax_meta(d) == {"t": 4}
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [4]
