"""Lint guard: every file write in fdtd3d_tpu/ routes through the
atomic writer (ISSUE 5 satellite; docs/ROBUSTNESS.md).

The durability contract is only as strong as its least-careful call
site: ONE stray ``open(path, "w")`` reintroduces torn-file-on-crash
behavior for that artifact. Round 12 (ISSUE 9): the hand-rolled AST
visitor moved into the static-analysis framework — this file is now a
thin tier-1 wrapper over the ``atomic-write`` rule
(fdtd3d_tpu/analysis/ast_rules.py; ``tools/fdtd_lint.py`` runs it
too). Append mode ('a') remains the one sanctioned exception (the
JSONL sinks); io.py's primitives and ``_write`` publish-closures
remain the allowed w-mode sites. The rule's known-bad fixture lives in
tests/fixtures/lint/bad_write.py.
"""

import os

from fdtd3d_tpu.analysis import Context
from fdtd3d_tpu.analysis.ast_rules import AtomicWriteRule


def test_every_write_routes_through_atomic_writer():
    findings, stats = AtomicWriteRule().run(Context())
    assert stats["files_scanned"] > 15, "scan surface collapsed?"
    assert not findings, (
        "file writes outside the atomic writer (io.atomic_open / "
        "io.atomic_publish; docs/ROBUSTNESS.md durability contract):\n"
        + "\n".join(f.format() for f in sorted(
            findings, key=lambda f: (f.file, f.line or 0))))


def test_lint_catches_a_plain_write(tmp_path):
    """The guard itself guards: a synthetic module with a bare
    open(..., 'w') must be flagged; an append-mode sink must not."""
    bad = tmp_path / "synthetic.py"
    bad.write_text("def f(p):\n    with open(p, 'w') as fh:\n"
                   "        fh.write('x')\n")
    ctx = Context(root=str(tmp_path),
                  paths=[(os.path.join("fdtd3d_tpu", "synthetic.py"),
                          str(bad))])
    findings, _ = AtomicWriteRule().run(ctx)
    assert len(findings) == 1 and "atomic" in findings[0].message

    ok = tmp_path / "sink.py"
    ok.write_text("def f(p):\n    open(p, 'a')\n")
    ctx2 = Context(root=str(tmp_path),
                   paths=[(os.path.join("fdtd3d_tpu", "sink.py"),
                           str(ok))])
    findings2, _ = AtomicWriteRule().run(ctx2)
    assert not findings2
