"""Lint guard: every file write in fdtd3d_tpu/ routes through the
atomic writer (ISSUE 5 satellite; pattern of test_lint_no_print.py).

The durability contract (docs/ROBUSTNESS.md) is only as strong as its
least-careful call site: ONE stray ``open(path, "w")`` reintroduces
torn-file-on-crash behavior for that artifact. This tier-1 guard makes
the contract structural, via the AST:

* truncating/creating ``open`` modes ('w', 'x', any 'b'/'+' variants)
  are banned outside fdtd3d_tpu/io.py;
* inside io.py they are allowed only in the atomic primitives
  themselves (``atomic_open``) and in ``_write`` closures — the
  documented convention for :func:`io.atomic_publish` writer callbacks,
  which receive the primitive's tmp path;
* ``ndarray.tofile`` / ``np.savez*`` (writers that bypass ``open``)
  are banned outside io.py for the same reason.

Append mode ('a') is the one sanctioned exception everywhere: the
telemetry/metrics JSONL sinks append one flushed line per record, which
is the crash-safe idiom for append-only logs — rewriting the whole file
per record would be the fragile choice. Read and 'r+' modes never
create/truncate and are out of scope (the fault harness's deliberate
corruption uses 'r+b').
"""

import ast
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIR = os.path.join(ROOT, "fdtd3d_tpu")

# io.py hosts the primitives; inside it, w-mode opens may appear only
# within these function names ("_write" = the atomic_publish writer-
# closure convention).
IO_ALLOWED_FUNCS = {"atomic_open", "_write"}

_BANNED_ATTRS = {"tofile", "savez", "savez_compressed"}


def _is_write_mode(mode: str) -> bool:
    return "w" in mode or "x" in mode


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath):
        self.relpath = relpath
        self.is_io = os.path.basename(relpath) == "io.py"
        self.func_stack = []
        self.offenders = []

    def _flag(self, node, what):
        self.offenders.append(
            f"{self.relpath}:{node.lineno}: {what}")

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _allowed_here(self):
        if not self.is_io:
            return False
        return bool(set(self.func_stack) & IO_ALLOWED_FUNCS)

    def visit_Call(self, node):
        func = node.func
        # open(path, "w"/"wb"/"x"...) — as a bare name or io.open
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
            if name in _BANNED_ATTRS and not self.is_io:
                self._flag(node, f".{name}() writes files directly — "
                                 f"route through fdtd3d_tpu.io's atomic "
                                 f"writer")
            if name == "open" and not (
                    isinstance(func.value, ast.Name)
                    and func.value.id in ("io", "builtins")):
                name = None  # os.open / gzip.open etc: not builtin open
        if name == "open":
            mode = "r"
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value,
                                                   ast.Constant):
                    mode = str(kw.value.value)
            literal = (len(node.args) < 2
                       or isinstance(node.args[1], ast.Constant))
            if (_is_write_mode(mode) or not literal) \
                    and not self._allowed_here():
                self._flag(node, f"open(..., {mode!r}) outside the "
                                 f"atomic writer — use io.atomic_open/"
                                 f"io.atomic_publish (append-mode JSONL "
                                 f"sinks are the one exception)")
        self.generic_visit(node)


def test_every_write_routes_through_atomic_writer():
    offenders = []
    for root, _dirs, files in os.walk(SCAN_DIR):
        if "__pycache__" in root:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            rel = os.path.relpath(path, ROOT)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=rel)
            v = _Visitor(rel)
            v.visit(tree)
            offenders.extend(v.offenders)
    assert not offenders, (
        "file writes outside the atomic writer (io.atomic_open / "
        "io.atomic_publish; docs/ROBUSTNESS.md durability contract):\n"
        + "\n".join(sorted(offenders)))


def test_lint_catches_a_plain_write(tmp_path):
    """The guard itself guards: a synthetic module with a bare
    open(..., 'w') must be flagged."""
    src = "def f(p):\n    with open(p, 'w') as fh:\n        fh.write('x')\n"
    v = _Visitor("synthetic.py")
    v.visit(ast.parse(src))
    assert len(v.offenders) == 1 and "atomic" in v.offenders[0]
    # and an append-mode sink is NOT flagged
    v2 = _Visitor("synthetic.py")
    v2.visit(ast.parse("def f(p):\n    open(p, 'a')\n"))
    assert not v2.offenders
