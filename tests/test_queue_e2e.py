"""Chip-free job-queue crash-safety e2e (ISSUE 15 acceptance).

One multi-tenant queue run drives the whole loop deterministically on
CPU: three jobs from two tenants — two coalescible (tenant acme, one
of them hit by a ``nan@...,lane=1`` fault inside the shared vmap
executable) and one solo (tenant globex, preempted mid-run at t=16) —
plus a quota rejection at the door, and a ``sched_crash@job=2`` fault
that kills the scheduler BETWEEN journal writes. A restarted
scheduler replays the append-only journal and drives every job to a
terminal state:

* the preempted job resumes from its committed checkpoint and its
  final snapshot is BIT-IDENTICAL to an uninterrupted run of the same
  spec;
* the coalesced pair provably shared one compiled executable (the
  exec-cache trace counter moved by exactly 2 for 3 jobs: one trace
  for the pair's shared vmap chunk, one for the solo job — the
  resumed dispatch re-used its executable);
* the lane-NaN tenant's job fails with the lane and first-bad-step
  named; the healthy lane's job completes;
* ``tools/fleet_report.py --json`` names per-tenant outcomes joined
  by run_id/job_id, and ``tools/slo_gate.py`` gates the journal via
  the queue-wait rule.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fdtd3d_tpu import exec_cache, faults, io, jobqueue, registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    monkeypatch.delenv("FDTD3D_AOT_CACHE_DIR", raising=False)
    faults.clear()
    yield
    faults.clear()


def _run_tool(args, cwd=ROOT, timeout=120):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run([sys.executable] + args,
                          capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=cwd)


def test_queue_crash_restart_reaches_all_terminal(tmp_path,
                                                  monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    base = ("--3d\n--same-size 12\n--time-steps 8\n"
            "--courant-factor 0.4\n--wavelength 0.008\n")
    spec_a = tmp_path / "a.txt"
    spec_a.write_text(base + "--eps 1.0\n")
    spec_b = tmp_path / "b.txt"
    spec_b.write_text(base + "--eps 2.0\n")
    spec_c = tmp_path / "c.txt"
    spec_c.write_text("--3d\n--same-size 12\n--time-steps 24\n"
                      "--courant-factor 0.4\n--wavelength 0.008\n"
                      "--point-source Ez\n--checkpoint-every 8\n")

    q = jobqueue.JobQueue(str(tmp_path / "queue"))
    # priorities: the coalescible pair dispatches first (the fault
    # plan's t thresholds rely on that deterministic order)
    a = q.submit(str(spec_a), tenant="acme", priority=1)
    b = q.submit(str(spec_b), tenant="acme", priority=1)
    c = q.submit(str(spec_c), tenant="globex", priority=0)
    # quota rejection, named: a third acme job over max_queued=2
    with pytest.raises(jobqueue.QuotaError,
                       match="'acme'.*max_queued"):
        q.submit(str(spec_a), tenant="acme",
                 policy=jobqueue.QuotaPolicy(max_queued=2))

    # dispatch 1 = the (a, b) batch: lane 1's NaN fires at its t=4
    # chunk boundary (batch horizon 8 < 16 keeps the preempt fault
    # out of it). dispatch 2 = c: preempted at t=16 (after the t=16
    # cadence snapshot), then sched_crash kills the scheduler before
    # c's post-run journal row lands.
    faults.install("nan@t=4,field=Ez,lane=1; preempt@t=16; "
                   "sched_crash@job=2")
    exec_cache.clear_memory()
    traces0 = exec_cache.stats()["traces"]
    sched = jobqueue.Scheduler(q, batch_chunk=4)
    with pytest.raises(faults.SimulatedPreemption,
                       match="scheduler crashed"):
        sched.serve()

    # the journal is exactly one transition short: c still "running"
    jobs = q.jobs()
    assert jobs[a]["status"] == "completed"
    assert jobs[b]["status"] == "failed"
    assert "lane 1 non-finite" in jobs[b]["reason"]
    assert jobs[c]["status"] == "running"
    # the coalesced pair shared ONE run (one executable, one group)
    assert jobs[a]["run_id"] == jobs[b]["run_id"]
    assert jobs[a]["group"] == jobs[b]["group"]
    assert jobs[a]["group"].startswith("g-")
    assert jobs[a]["lane"] == 0 and jobs[b]["lane"] == 1

    # restart: the incident is over (the fault plan's fired flags ARE
    # the record); a fresh scheduler replays the journal
    faults.clear()
    out = jobqueue.Scheduler(q).serve()
    jobs = out["jobs"]
    assert {j["status"] for j in jobs.values()} <= \
        set(jobqueue.TERMINAL_STATES)
    assert jobs[c]["status"] == "completed" and jobs[c]["t"] == 24
    assert jobs[a]["status"] == "completed"
    assert jobs[b]["status"] == "failed"

    # trace-counter proof: 3 jobs, 2 executables — the pair shared
    # one vmap chunk; the resumed solo dispatch re-used its cached
    # n=8 chunk executable instead of tracing again
    assert exec_cache.stats()["traces"] - traces0 == 2

    # bit-identical resume: an uninterrupted run of c's spec ends in
    # the same final snapshot, array for array
    monkeypatch.delenv("FDTD3D_RUN_REGISTRY")
    from fdtd3d_tpu import cli
    ref_dir = str(tmp_path / "ref")
    rc = cli.main(["--cmd-from-file", str(spec_c),
                   "--save-dir", ref_dir])
    assert rc == 0
    ref_ck = io.find_latest_checkpoint(ref_dir)
    job_ck = io.find_latest_checkpoint(q.job_dir(c))
    sref, mref = io.load_checkpoint(ref_ck)
    sjob, mjob = io.load_checkpoint(job_ck)
    assert mref["t"] == mjob["t"] == 24

    def _leaves(tree, prefix=""):
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                yield from _leaves(v, f"{prefix}{k}/")
            else:
                yield f"{prefix}{k}", v

    ref_leaves = dict(_leaves(sref))
    job_leaves = dict(_leaves(sjob))
    assert set(ref_leaves) == set(job_leaves)
    for key, arr in ref_leaves.items():
        assert np.array_equal(arr, job_leaves[key]), key

    # fleet view: per-tenant outcomes joined by run_id/job_id. The
    # killed first dispatch of c stays "running" (a run killed
    # without close is exactly that); the batch folded "recovered"
    # (lane isolation IS its recovery); the resumed run completed.
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    proc = _run_tool([os.path.join(TOOLS, "fleet_report.py"), reg,
                      "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rollup = json.loads(proc.stdout)
    assert rollup["fleet"]["by_status"] == \
        {"completed": 1, "recovered": 1, "running": 1}
    runs = rollup["runs"]
    batch_run = runs[jobs[a]["run_id"]]
    assert batch_run["job_id"] == jobs[a]["group"]
    assert batch_run["tenant"] == "acme"
    assert batch_run["status"] == "recovered"
    solo_run = runs[jobs[c]["run_id"]]
    assert solo_run["job_id"] == c
    assert solo_run["tenant"] == "globex"
    assert solo_run["status"] == "completed"
    # the unhealthy tenant (lane 1 = job b) is named in the rollup
    assert any(t["run"] == jobs[b]["run_id"] and t["lane"] == 1
               for t in rollup["fleet"]["unhealthy_tenants"])

    # the journal itself gates: the queue-wait-p95 rule judges the
    # dispatch rows (OK at the default 300s objective), exit 0
    proc = _run_tool([os.path.join(TOOLS, "slo_gate.py"),
                      q.journal])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue-wait-p95" in proc.stdout
    assert "OK" in proc.stdout

    # and the operator CLI folds the same journal
    proc = _run_tool([os.path.join(TOOLS, "fdtd_queue.py"),
                      "status", "--queue-dir", q.dirpath, "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    folded = json.loads(proc.stdout)["jobs"]
    assert folded[c]["status"] == "completed"
    assert folded[b]["status"] == "failed"
