"""Static cost ledger (fdtd3d_tpu/costs.py): per-section attribution.

ISSUE 3 acceptance, asserted deterministically on CPU (pure tracing,
no compile, no chip): the ledger attributes >= 95% of per-step flops
AND bytes to named sections for every production step kind (jnp,
pallas, pallas_packed, pallas_packed_tb, pallas_packed_ds), the schema
validates, and the roofline lane turns an HBM GB/s calibration into a
modeled step time. Round 8 adds the temporal-blocked kernel's
"roofline moved" gate: its per-step field bytes must be <= 0.55x the
single-step packed kernel's on the same config.
"""

import json

import pytest

from fdtd3d_tpu import costs, telemetry

KINDS = costs.STEP_KINDS


@pytest.fixture(scope="module")
def ledgers():
    """One traced ledger per step kind (module-scoped: tracing the
    packed kernels is the expensive part of this file)."""
    out = {}
    for kind in KINDS:
        cfg = costs.config_for_kind(kind)
        out[kind] = costs.chunk_ledger(cfg, n_steps=8, kind=kind)
    return out


@pytest.mark.parametrize("kind", KINDS)
def test_ledger_validates(ledgers, kind):
    led = ledgers[kind]
    costs.validate_ledger(led)
    assert led["step_kind"] == kind
    # json round-trip clean (the artifact is a file format)
    costs.validate_ledger(json.loads(json.dumps(led)))


@pytest.mark.parametrize("kind", KINDS)
def test_ledger_coverage_95(ledgers, kind):
    """THE acceptance bar: >= 95% of per-step flops and bytes land on
    named sections (not 'unattributed') for every step kind."""
    ps = ledgers[kind]["per_step"]
    assert ps["coverage_flops"] >= 0.95, \
        f"{kind}: only {ps['coverage_flops']:.1%} of flops attributed"
    assert ps["coverage_bytes"] >= 0.95, \
        f"{kind}: only {ps['coverage_bytes']:.1%} of bytes attributed"
    assert ps["flops"] > 0 and ps["bytes"] > 0


@pytest.mark.parametrize("kind", KINDS)
def test_ledger_sections_are_named_spans(ledgers, kind):
    led = ledgers[kind]
    for sec in led["sections"]:
        assert sec in telemetry.GRAPH_SPANS + ("unattributed",), sec
    # fractions sum to ~1 within each table
    for table in (led["sections"], led["per_chunk_sections"]):
        if table:
            assert sum(r["bytes_frac"] for r in table.values()) == \
                pytest.approx(1.0, abs=1e-3)


def test_ledger_expected_sections(ledgers):
    """The probe config (CPML + point source) must surface the
    physically-expected sections per kind."""
    assert {"E-update", "H-update", "cpml", "source"} <= \
        set(ledgers["jnp"]["sections"])
    assert "packed-kernel" in ledgers["pallas_packed"]["sections"]
    assert "packed-kernel" in ledgers["pallas_packed_ds"]["sections"]
    assert "packed-kernel-tb" in \
        ledgers["pallas_packed_tb"]["sections"]
    # two-pass kernels attribute their family kernels to E/H-update
    assert {"E-update", "H-update"} <= set(ledgers["pallas"]["sections"])
    # the health reduction is per-chunk, never per-step
    for kind in KINDS:
        assert "health" in ledgers[kind]["per_chunk_sections"]
        assert "health" not in ledgers[kind]["sections"]


# Round-12 acceptance bounds: per-step field HBM bytes of the depth-k
# temporal-blocked kernel vs the single-step packed kernel on the same
# config (12 field volumes per k steps + per-pass overheads).
TB_RATIO_BOUNDS = {2: 0.55, 3: 0.40, 4: 0.32}


@pytest.mark.parametrize("depth", sorted(TB_RATIO_BOUNDS))
def test_tb_ledger_roofline_moved(monkeypatch, depth):
    """Round-8/12 acceptance gate, CPU-deterministic: the depth-k
    temporal-blocked kernel's PER-STEP field bytes — the packed-kernel
    section's pallas_call charge, i.e. the modeled HBM traffic — must
    be <= {2: 0.55, 3: 0.40, 4: 0.32}[k] x the single-step packed
    kernel's on the same config (the kernel moves 12 field volumes per
    k steps instead of per one)."""
    monkeypatch.setenv("FDTD3D_TB_DEPTH", str(depth))
    cfg = costs.config_for_kind("pallas_packed_tb")
    tb = costs.chunk_ledger(cfg, n_steps=12, kind="pallas_packed_tb")
    pk = costs.chunk_ledger(costs.config_for_kind("pallas_packed"),
                            n_steps=12, kind="pallas_packed")
    assert tb["steps_per_call"] == depth
    assert pk["steps_per_call"] == 1
    tb_b = tb["sections"]["packed-kernel-tb"]["bytes"] / tb["cells"]
    pk_b = pk["sections"]["packed-kernel"]["bytes"] / pk["cells"]
    bound = TB_RATIO_BOUNDS[depth]
    assert tb_b <= bound * pk_b, \
        f"k={depth} tb kernel {tb_b:.1f} B/cell/step vs packed " \
        f"{pk_b:.1f} (bound {bound})"


@pytest.mark.parametrize("depth", sorted(TB_RATIO_BOUNDS))
def test_tb_ledger_total_bytes_sourceless(monkeypatch, depth):
    """Same per-depth gate on the whole per-step byte total,
    sourceless (the sourced packed kernel carries post-kernel patch
    machinery whose unfused byte bound would flatter the ratio):
    exactly the k-fold temporal-blocking claim, every operand
    charged."""
    import dataclasses

    from fdtd3d_tpu.config import PointSourceConfig
    monkeypatch.setenv("FDTD3D_TB_DEPTH", str(depth))
    vals = {}
    for kind in ("pallas_packed", "pallas_packed_tb"):
        cfg = dataclasses.replace(
            costs.config_for_kind(kind),
            point_source=PointSourceConfig(enabled=False))
        led = costs.chunk_ledger(cfg, n_steps=12, kind=kind)
        vals[kind] = led["per_step"]["bytes_per_cell"]
    ratio = vals["pallas_packed_tb"] / vals["pallas_packed"]
    bound = TB_RATIO_BOUNDS[depth]
    assert ratio <= bound, \
        f"k={depth} per-step bytes ratio {ratio:.3f} > {bound}"


def test_tb_ledger_odd_horizon_raises():
    """An odd n_steps would hide tail-step cost in the per-chunk table;
    the ledger refuses instead of silently blurring the split."""
    cfg = costs.config_for_kind("pallas_packed_tb")
    with pytest.raises(ValueError, match="steps_per_call"):
        costs.chunk_ledger(cfg, n_steps=7, kind="pallas_packed_tb")


def test_ds_flops_exceed_f32(ledgers):
    """The double-single kernel's EFT arithmetic must show up: more
    flops per cell than the plain-f32 packed kernel."""
    f32 = ledgers["pallas_packed"]["per_step"]["flops_per_cell"]
    ds = ledgers["pallas_packed_ds"]["per_step"]["flops_per_cell"]
    assert ds > 2.0 * f32


def test_roofline_lane():
    cfg = costs.config_for_kind("jnp")
    led = costs.chunk_ledger(cfg, n_steps=8, kind="jnp", hbm_gbps=500.0)
    r = led["roofline"]
    assert r is not None and r["hbm_gbps"] == 500.0
    ps = led["per_step"]
    assert r["modeled_step_ms"] == pytest.approx(
        ps["bytes"] / (500.0 * 1e9) * 1e3)
    assert r["modeled_mcells_per_s"] == pytest.approx(
        led["cells"] / (ps["bytes"] / (500.0 * 1e9)) / 1e6)
    # no calibration -> no roofline, never a fabricated one
    led2 = costs.chunk_ledger(cfg, n_steps=8, kind="jnp", hbm_gbps=None)
    telemetry.set_hbm_probe(None)
    assert led2["roofline"] is None or \
        led2["roofline"]["hbm_gbps"] > 0  # (env-set probe tolerated)


def test_forced_kind_mismatch_raises():
    """A config outside the forced kernel's scope must raise, not
    silently ledger the fallback graph."""
    import dataclasses
    cfg = dataclasses.replace(costs.config_for_kind("pallas_packed"),
                              use_pallas=False)
    with pytest.raises(RuntimeError, match="step kind"):
        costs.chunk_ledger(cfg, kind="pallas_packed")


def test_validate_ledger_rejects_malformed(ledgers):
    with pytest.raises(ValueError, match="schema"):
        costs.validate_ledger({"schema": "nope"})
    bad = json.loads(json.dumps(ledgers["jnp"]))
    bad["per_step"]["coverage_bytes"] = 1.7
    with pytest.raises(ValueError, match="out of"):
        costs.validate_ledger(bad)
    bad2 = json.loads(json.dumps(ledgers["jnp"]))
    del bad2["sections"]
    with pytest.raises(ValueError, match="sections"):
        costs.validate_ledger(bad2)


def test_costs_cli(tmp_path, capsys):
    out = tmp_path / "ledger.json"
    rc = costs.main(["--kind", "jnp", "--same-size", "16",
                     "--pml-size", "3", "--hbm-gbps", "600",
                     "--out", str(out)])
    assert rc == 0
    led = json.loads(out.read_text())
    costs.validate_ledger(led)
    assert led["roofline"]["hbm_gbps"] == 600.0
    # the CLI's stdout IS the ledger (log.report)
    assert json.loads(capsys.readouterr().out)["step_kind"] == "jnp"


# ---------------------------------------------------------------------------
# Lane-capable batched ledgers (round 16): the vmapped packed runner,
# normalized PER-LANE per-step, must cost what solo packed costs.

BATCH_HBM_BOUND = 1.15   # per-lane packed field bytes vs solo packed


@pytest.fixture(scope="module")
def batch_ledgers():
    """Solo + 3-lane batched packed ledgers on one config (module-
    scoped: the batched trace vmaps the packed kernel)."""
    cfg = costs.config_for_kind("pallas_packed")
    return {
        "solo": costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed"),
        "b3": costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed",
                                 batch=3),
    }


def test_batch_ledger_validates_and_keys(batch_ledgers):
    b3 = batch_ledgers["b3"]
    costs.validate_ledger(b3)
    costs.validate_ledger(json.loads(json.dumps(b3)))
    assert b3["step_kind"] == "pallas_packed"
    assert b3["batch"] == 3
    assert batch_ledgers["solo"]["batch"] is None
    assert "batch" in costs.LEDGER_KEYS
    # old (pre-batch) ledger files keep validating: the key is emitted,
    # never required
    old = json.loads(json.dumps(batch_ledgers["solo"]))
    del old["batch"]
    costs.validate_ledger(old)


def test_batch_ledger_coverage_95(batch_ledgers):
    """Satellite acceptance: >= 95% of the BATCHED trace's per-step
    flops and bytes land on named sections."""
    ps = batch_ledgers["b3"]["per_step"]
    assert ps["coverage_flops"] >= 0.95
    assert ps["coverage_bytes"] >= 0.95
    assert ps["flops"] > 0 and ps["bytes"] > 0


def test_batch_ledger_per_lane_hbm_gate(batch_ledgers):
    """THE CPU gate: batched per-lane per-step packed-kernel field HBM
    bytes <= 1.15x the solo packed kernel's on the same config — the
    batch executes at packed-kernel cost, not vmap-jnp cost."""
    solo, b3 = batch_ledgers["solo"], batch_ledgers["b3"]
    s = solo["sections"]["packed-kernel"]["bytes"] / solo["cells"]
    b = b3["sections"]["packed-kernel"]["bytes"] / b3["cells"]
    assert b <= BATCH_HBM_BOUND * s, \
        f"batched per-lane packed bytes {b:.1f}/cell vs solo {s:.1f} " \
        f"(bound {BATCH_HBM_BOUND}x)"
    # and the whole per-step byte total stays in the same band
    assert b3["per_step"]["bytes_per_cell"] <= \
        BATCH_HBM_BOUND * solo["per_step"]["bytes_per_cell"]


def test_batch_ledger_tb_kind(monkeypatch):
    """The depth-k temporal-blocked kernel is lane-capable too: a
    batched tb trace engages pallas_packed_tb and keeps per-lane
    per-step parity with the solo tb ledger."""
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "2")
    cfg = costs.config_for_kind("pallas_packed_tb")
    solo = costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed_tb")
    b3 = costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed_tb",
                            batch=3)
    assert b3["step_kind"] == "pallas_packed_tb"
    assert b3["steps_per_call"] == solo["steps_per_call"] == 2
    s = solo["sections"]["packed-kernel-tb"]["bytes"] / solo["cells"]
    b = b3["sections"]["packed-kernel-tb"]["bytes"] / b3["cells"]
    assert b <= BATCH_HBM_BOUND * s


def test_batch_ledger_sharded_one_halo_exchange():
    """Sharded batched trace: the whole batch shares ONE halo exchange
    per step — per-lane halo bytes equal solo's and the per-lane
    message share is solo's / B (fractional by design)."""
    cfg = costs.config_for_kind("pallas_packed", n=16, pml=2)
    solo = costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed",
                              topology=(2, 2, 2))
    b3 = costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed",
                            topology=(2, 2, 2), batch=3)
    cs, cb = solo["comm"]["per_step"], b3["comm"]["per_step"]
    assert cb["ppermute_bytes_per_chip"] == \
        pytest.approx(cs["ppermute_bytes_per_chip"])
    assert cb["ppermute_messages"] == \
        pytest.approx(cs["ppermute_messages"] / 3)
