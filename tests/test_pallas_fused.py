"""Single-pass fused E+H kernel (ops/pallas_fused.py) vs the jnp step.

The fused kernel's scope is the no-post-pass subset (no TFSF/point
source/x-PML, unsharded); within it, parity with the jnp step must hold
at f32 roundoff, and out-of-scope configs must fall back to the two-pass
kernels ("pallas") rather than silently degrade.
"""

import dataclasses

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3)


def _run(use_pallas, **kw):
    sim = Simulation(SimConfig(**BASE, use_pallas=use_pallas, **kw))
    key = jax.random.PRNGKey(0)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))
    sim.run()
    return sim


def _parity(tol=2e-6, **kw):
    j = _run(False, **kw)
    p = _run(True, **kw)
    assert p.step_kind == "pallas_fused", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"


def test_fused_vacuum_parity():
    _parity()


def test_fused_yz_cpml_parity():
    _parity(pml=PmlConfig(size=(0, 3, 3)))


def test_fused_metamaterial_parity():
    _parity(pml=PmlConfig(size=(0, 3, 3)),
            materials=MaterialsConfig(
                use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                          radius=3),
                use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
                drude_m_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                            radius=3)))


def test_fused_material_array_parity():
    _parity(materials=MaterialsConfig(
        eps=2.0, eps_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                         radius=4, value=6.0)))


def test_fused_bf16_parity():
    j = _run(False, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    p = _run(True, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    assert p.step_kind == "pallas_fused"
    for c in ("Ez", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-2, f"{c}: rel {rel:.2e}"


def test_fused_uneven_tiles():
    """Non-power-of-two x extent: exercises the clamped last-tile halo
    index maps — and the fields must MATCH, not just run."""
    cfg = dict(BASE)
    cfg["size"] = (12, 16, 16)

    def run(up):
        sim = Simulation(SimConfig(**cfg, use_pallas=up,
                                   pml=PmlConfig(size=(0, 3, 3))))
        key = jax.random.PRNGKey(2)
        for grp in ("E", "H"):
            for c in list(sim.state[grp]):
                key, k2 = jax.random.split(key)
                sim.set_field(c, 0.01 * np.asarray(
                    jax.random.normal(k2, sim.state[grp][c].shape)))
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas_fused"
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


@pytest.mark.parametrize("name,kw,expect", [
    ("tfsf", dict(pml=PmlConfig(size=(0, 3, 3)),
                  tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2))),
     "pallas"),
    ("point-source", dict(point_source=PointSourceConfig(
        enabled=True, component="Ez", position=(8, 8, 8))), "pallas"),
    ("x-pml", dict(pml=PmlConfig(size=(3, 3, 3))), "pallas"),
])
def test_out_of_scope_falls_back_to_two_pass(name, kw, expect):
    sim = Simulation(SimConfig(**BASE, use_pallas=True, **kw))
    assert sim.step_kind == expect, f"{name}: {sim.step_kind}"


def test_sharded_falls_back_to_two_pass():
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sim.step_kind == "pallas"
