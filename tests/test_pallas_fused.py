"""Single-pass fused E+H kernel (ops/pallas_fused.py) vs the jnp step.

The fused kernel covers the full single-chip scope — CPML on any axes,
TFSF, point source, Drude — via thin-patch H corrections (the kernel
computes H from the pre-patch E; apply_patch_h_corrections adds the
curl of the E patches). Parity with the jnp step must hold at f32
roundoff INCLUDING the psi recursion state; out-of-scope configs
(sharded, slab-unfit PML) must fall back to the two-pass kernels
("pallas") rather than silently degrade.
"""

import dataclasses

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation


@pytest.fixture(autouse=True)
def _no_packed(monkeypatch):
    """Pin the dispatch to the recompute-fused kernel under test: the
    packed pipelined kernel (ops/pallas_packed.py, round 4) outranks it
    and would otherwise take every eligible config here."""
    monkeypatch.setenv("FDTD3D_NO_PACKED", "1")

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3)


def _run(use_pallas, **kw):
    sim = Simulation(SimConfig(**BASE, use_pallas=use_pallas, **kw))
    key = jax.random.PRNGKey(0)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))
    sim.run()
    return sim


def _parity(tol=2e-6, **kw):
    j = _run(False, **kw)
    p = _run(True, **kw)
    assert p.step_kind == "pallas_fused", p.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"


def test_fused_vacuum_parity():
    _parity()


def test_fused_yz_cpml_parity():
    _parity(pml=PmlConfig(size=(0, 3, 3)))


def test_fused_metamaterial_parity():
    _parity(pml=PmlConfig(size=(0, 3, 3)),
            materials=MaterialsConfig(
                use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                          radius=3),
                use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
                drude_m_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                            radius=3)))


def test_fused_material_array_parity():
    _parity(materials=MaterialsConfig(
        eps=2.0, eps_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                         radius=4, value=6.0)))


def test_fused_bf16_parity():
    j = _run(False, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    p = _run(True, dtype="bfloat16", pml=PmlConfig(size=(0, 3, 3)))
    assert p.step_kind == "pallas_fused"
    for c in ("Ez", "Hy"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-2, f"{c}: rel {rel:.2e}"


def test_fused_uneven_tiles():
    """Non-power-of-two x extent: exercises the clamped last-tile halo
    index maps — and the fields must MATCH, not just run."""
    cfg = dict(BASE)
    cfg["size"] = (12, 16, 16)

    def run(up):
        sim = Simulation(SimConfig(**cfg, use_pallas=up,
                                   pml=PmlConfig(size=(0, 3, 3))))
        key = jax.random.PRNGKey(2)
        for grp in ("E", "H"):
            for c in list(sim.state[grp]):
                key, k2 = jax.random.split(key)
                sim.set_field(c, 0.01 * np.asarray(
                    jax.random.normal(k2, sim.state[grp][c].shape)))
        sim.run()
        return sim
    j = run(False)
    p = run(True)
    assert p.step_kind == "pallas_fused"
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 2e-6, f"{c}: rel {rel:.2e}"


def test_fused_x_pml_parity():
    """x-CPML: kernel computes the plain x curl; x_slab_post patches E,
    the H correction is the curl of those patches."""
    _parity(pml=PmlConfig(size=(3, 3, 3)))


def test_fused_tfsf_parity():
    """Oblique TFSF: E face patches feed the H curl correction; the
    H-side consistency corrections sample Einc as in the jnp path."""
    _parity(pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                            angle_teta=30.0, angle_phi=40.0,
                            angle_psi=15.0))


def test_fused_tfsf_in_slab_parity():
    """margin=1 pushes the H patch planes INTO the y/z CPML slabs —
    exercises the psi' correction at the slab overlap, verified on the
    psi state itself (errors there would accumulate silently)."""
    j = _run(False, pml=PmlConfig(size=(3, 3, 3)),
             tfsf=TfsfConfig(enabled=True, margin=(1, 1, 1),
                             angle_teta=30.0, angle_phi=40.0,
                             angle_psi=15.0))
    p = _run(True, pml=PmlConfig(size=(3, 3, 3)),
             tfsf=TfsfConfig(enabled=True, margin=(1, 1, 1),
                             angle_teta=30.0, angle_phi=40.0,
                             angle_psi=15.0))
    assert p.step_kind == "pallas_fused"
    for grp in ("psi_E", "psi_H"):
        for k in j.state[grp]:
            a = np.asarray(j.state[grp][k])
            b = np.asarray(p.state[grp][k])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-6, f"{grp}/{k}: rel {rel:.2e}"


def test_fused_point_source_and_everything_parity():
    """The kitchen sink: x/y/z CPML + axis-aligned TFSF + point source
    + dual Drude — the bench/flagship feature set in one config."""
    _parity(pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(5, 9, 7)),
            materials=MaterialsConfig(
                use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
                drude_sphere=SphereConfig(enabled=True, center=(8, 8, 8),
                                          radius=3),
                use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
                drude_m_sphere=SphereConfig(enabled=True,
                                            center=(8, 8, 8), radius=3)))


# (No thin-PML fallback test: config validation requires
# 2*npml + 4 <= n while slab compaction needs only n > 2*npml + 2, so
# every VALID unsharded config slab-fits; the slab check in
# make_fused_eh_step is a safety net for future layout changes.)


def test_h_inputs_never_donated(monkeypatch):
    """Donation-safety tripwire (VERDICT r2 item 10): the fused kernel
    reads H BACKWARD (the bwd-halo plane belongs to the previous tile,
    already overwritten under the sequential grid order), so H inputs
    must never appear in input_output_aliases. Interpreter mode cannot
    surface the hazard at runtime — assert the structure instead."""
    from jax.experimental import pallas as pl

    from fdtd3d_tpu import solver
    from fdtd3d_tpu.ops import pallas_fused

    captured = {}
    real_call = pl.pallas_call

    def spy(kernel, **kw):
        captured["aliases"] = dict(kw.get("input_output_aliases") or {})
        return real_call(kernel, **kw)

    monkeypatch.setattr(pallas_fused.pl, "pallas_call", spy)
    cfg = SimConfig(**BASE, pml=PmlConfig(size=(3, 3, 3)),
                    materials=MaterialsConfig(
                        use_drude=True, eps_inf=1.5, omega_p=1e11,
                        gamma=1e10,
                        drude_sphere=SphereConfig(enabled=True,
                                                  center=(8, 8, 8),
                                                  radius=3)))
    static = solver.build_static(cfg)
    step = pallas_fused.make_fused_eh_step(static)
    assert step is not None and captured
    mode = static.mode
    ne, nh = len(mode.e_components), len(mode.h_components)
    # operand order: E in (ne) | E extra (ne) | H in (nh) | ...
    h_in = set(range(2 * ne, 2 * ne + nh))
    donated = set(captured["aliases"])
    assert not (h_in & donated), (
        f"H inputs {sorted(h_in & donated)} are donated — backward "
        f"halo reads make this a correctness hazard on TPU")


def test_sharded_falls_back_to_two_pass():
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True, pml=PmlConfig(size=(0, 3, 3)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(1, 2, 2))))
    assert sim.step_kind == "pallas"
