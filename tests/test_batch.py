"""vmap-batched scenario execution (fdtd3d_tpu/batch.py) — ISSUE 12.

Acceptance, CPU-deterministic: a 3-scenario ``run_batch`` compiles
ONCE (exec-cache counter-asserted) while matching sequential runs
bit-for-bit per lane (vacuum AND a CPML+point-source case); a
fault-injected NaN in one lane trips only that lane's health flag;
eligibility violations are NAMED errors; the sharded batch's compiled
module carries the same halo-exchange count as a single run (one
exchange for the whole batch, not B of them).
"""

import dataclasses
import json
import re

import numpy as np
import pytest

from fdtd3d_tpu import exec_cache, faults, telemetry
from fdtd3d_tpu.batch import BatchSimulation
from fdtd3d_tpu.config import (MaterialsConfig, OutputConfig,
                               ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig,
                               SphereConfig)
from fdtd3d_tpu.sim import Simulation


def _cfg(n=12, eps=1.0, amp=1.0, steps=8, **kw):
    kw.setdefault("pml", PmlConfig(size=(3, 3, 3)))
    kw.setdefault("materials", MaterialsConfig(eps=eps))
    return SimConfig(
        scheme="3D", size=(n, n, n), time_steps=steps, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(n // 2,) * 3,
                                       amplitude=amp), **kw)


def _sequential(cfg, steps):
    sim = Simulation(dataclasses.replace(cfg, use_pallas=False))
    sim.advance(steps)
    return sim


def _assert_lane_equal(bsim, lane, sim):
    for group in ("E", "H"):
        for comp in sim.state[group]:
            a = np.asarray(sim.state[group][comp])
            b = bsim.lane_field(lane, comp)
            assert np.array_equal(a, b), \
                f"lane {lane} {comp} diverges (max " \
                f"{np.abs(a - b).max()})"


def test_batch_parity_cpml_source_bit_identical():
    """3 lanes with different materials AND source amplitudes (CPML +
    point source — the full jnp graph) == 3 sequential runs, bit for
    bit, from ONE compiled executable."""
    cfgs = [_cfg(eps=1.0, amp=1.0), _cfg(eps=1.5, amp=2.0),
            _cfg(eps=2.0, amp=0.5)]
    s0 = exec_cache.stats()
    bsim = Simulation.run_batch(cfgs)
    s1 = exec_cache.stats()
    assert s1["traces"] - s0["traces"] == 1, \
        "B scenarios must cost exactly one trace"
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _sequential(cfg, 8))
    assert bsim.lane_field(1, "Ez").max() > 0


def test_batch_parity_vacuum_no_pml():
    cfgs = [_cfg(pml=PmlConfig(), amp=a) for a in (1.0, 3.0)]
    bsim = Simulation.run_batch(cfgs)
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _sequential(cfg, 8))


def test_batch_material_grid_lanes():
    """Lanes may differ in material VALUES including sphere grids —
    as long as every lane has the grid (structure matches)."""
    def sphere(v):
        return MaterialsConfig(eps_sphere=SphereConfig(
            enabled=True, center=(6.0, 6.0, 6.0), radius=3.0, value=v))
    cfgs = [_cfg(materials=sphere(2.0)), _cfg(materials=sphere(4.0))]
    bsim = Simulation.run_batch(cfgs)
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _sequential(cfg, 8))


def test_batch_nan_trips_only_its_lane(tmp_path):
    """faults ``nan@t=4,field=Ez,lane=1``: lane 1 flags non-finite,
    lanes 0/2 stay healthy AND bit-identical to clean sequential runs;
    the batch_lane telemetry rows carry the per-lane verdicts."""
    path = tmp_path / "t.jsonl"
    cfgs = [_cfg(), _cfg(), _cfg()]
    cfgs[0] = dataclasses.replace(
        cfgs[0], output=OutputConfig(telemetry_path=str(path),
                                     check_finite=True))
    faults.clear()
    faults.install("nan@t=4,field=Ez,lane=1")
    try:
        bsim = BatchSimulation(cfgs)
        bsim.advance(4)
        bsim.advance(4)
        bsim.close()
    finally:
        faults.clear()
    assert bsim.lane_finite == [True, False, True]
    assert bsim.lane_first_unhealthy_t == [None, 8, None]
    # the healthy lanes' physics is untouched by lane 1's NaN
    clean = _sequential(_cfg(), 8)
    _assert_lane_equal(bsim, 0, clean)
    _assert_lane_equal(bsim, 2, clean)
    assert not np.isfinite(bsim.lane_field(1, "Ez")).all()
    recs = telemetry.read_jsonl(str(path))
    lanes = [r for r in recs if r["type"] == "batch_lane"]
    assert len(lanes) == 6   # 3 lanes x 2 chunks
    final = {r["lane"]: r for r in lanes if r["t"] == 8}
    assert final[1]["finite"] is False and final[0]["finite"] is True
    # a lane's NaN counters are null, not NaN literals (RFC 8259)
    assert final[1]["max_e"] is None
    # the aggregate chunk row says the batch was not all-finite
    agg = [r for r in recs if r["type"] == "chunk"]
    assert agg and agg[-1]["finite"] is False


def test_batch_lane_scope_validation():
    faults.clear()
    faults.install("nan@t=0,field=Ez,lane=1")
    try:
        sim = Simulation(_cfg())
        with pytest.raises(ValueError, match="lane= scope only"):
            sim.advance(4)
    finally:
        faults.clear()
    faults.install("nan@t=0,field=Ez")
    try:
        bsim = BatchSimulation([_cfg(), _cfg()])
        with pytest.raises(ValueError, match="needs an explicit lane"):
            bsim.advance(4)
    finally:
        faults.clear()


def test_batch_eligibility_named_errors():
    # graph-shaping divergence: named field in the error (the first
    # differing field for a grid change is the source position default)
    with pytest.raises(ValueError,
                       match="graph-shaping config field"):
        BatchSimulation([_cfg(n=12), _cfg(n=16)])
    with pytest.raises(ValueError, match="time_steps"):
        BatchSimulation([_cfg(steps=8), _cfg(steps=16)])
    # structural materials divergence: the offending leaf is named
    grid = MaterialsConfig(eps_sphere=SphereConfig(
        enabled=True, center=(6.0, 6.0, 6.0), radius=3.0, value=2.0))
    with pytest.raises(ValueError, match="not same-shape"):
        BatchSimulation([_cfg(), _cfg(materials=grid)])


def test_batch_max_knob(monkeypatch):
    monkeypatch.setenv("FDTD3D_BATCH_MAX", "2")
    with pytest.raises(ValueError, match="FDTD3D_BATCH_MAX"):
        BatchSimulation([_cfg(), _cfg(), _cfg()])
    monkeypatch.setenv("FDTD3D_BATCH_MAX", "nope")
    with pytest.raises(ValueError, match="integer"):
        BatchSimulation([_cfg(), _cfg()])


def _count_collective_permutes(compiled) -> int:
    txt = compiled.as_text()
    return len(re.findall(r" collective-permute(?:-start)?\(",
                          txt))


def test_batch_sharded_one_halo_exchange_for_all_lanes():
    """Sharded batch: per-lane parity vs a sharded sequential run AND
    the compiled module's halo-exchange op count equals the single
    run's — the lanes ride ONE exchange, not B."""
    par = ParallelConfig(topology="manual", manual_topology=(2, 2, 2))
    cfgs = [_cfg(n=16, amp=1.0, parallel=par),
            _cfg(n=16, amp=2.0, parallel=par)]
    bsim = BatchSimulation(cfgs)
    bsim.advance(8)
    for lane, cfg in enumerate(cfgs):
        sim = _sequential(cfg, 8)
        for comp in ("Ez", "Hy"):
            a = np.asarray(sim.field(comp))
            assert np.array_equal(a, bsim.lane_field(lane, comp))
    single = Simulation(dataclasses.replace(cfgs[0],
                                            use_pallas=False))
    single.advance(8)
    n_batch = _count_collective_permutes(bsim._compiled[8])
    n_single = _count_collective_permutes(single._compiled[8])
    assert n_batch > 0
    assert n_batch == n_single, \
        f"batched module has {n_batch} collective-permutes vs the " \
        f"single run's {n_single} — lanes must share the exchange"


def test_cli_batch_smoke(tmp_path, capsys):
    from fdtd3d_tpu import cli
    spec = ("--3d\n--same-size 12\n--time-steps 8\n--use-pml\n"
            "--pml-size 3\n--point-source Ez\n"
            "--point-source-amplitude {amp}\n--log-level 1\n")
    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_text(spec.format(amp=1.0))
    b.write_text(spec.format(amp=2.0))
    tpath = tmp_path / "t.jsonl"
    rc = cli.main(["--batch", str(a), str(b),
                   "--telemetry", str(tpath), "--check-finite"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "batch lane 0: healthy" in out
    assert "batch lane 1: healthy" in out
    assert "2 lanes x 8 steps" in out
    recs = telemetry.read_jsonl(str(tpath))
    types = {r["type"] for r in recs}
    assert {"run_start", "batch_lane", "chunk", "run_end"} <= types
    start = next(r for r in recs if r["type"] == "run_start")
    assert start["batch"] == 2


def test_batch_run_chunked_matches_single_chunk():
    """run(chunk=4) (two dispatches) == run() (one dispatch) — chunk
    boundaries are observability seams, not physics."""
    cfgs = [_cfg(amp=1.0), _cfg(amp=2.0)]
    b1 = BatchSimulation(cfgs)
    b1.run(8)
    b2 = BatchSimulation(cfgs)
    b2.run(8, chunk=4)
    for lane in range(2):
        assert np.array_equal(b1.lane_field(lane, "Ez"),
                              b2.lane_field(lane, "Ez"))


def test_batch_ds_refused_with_named_error():
    """float32x2 does not batch on this jax (the ds step's
    optimization_barrier has no vmap batching rule): a NAMED
    eligibility error, never a raw NotImplementedError mid-compile."""
    with pytest.raises(ValueError, match="float32x2 scenarios"):
        BatchSimulation([_cfg(dtype="float32x2"),
                         _cfg(dtype="float32x2")])


def test_batch_nan_chip_and_lane_scopes_compose():
    """Review finding (round 15): chip= must not be silently ignored
    on a batched sim — nan@...,chip=C,lane=L lands at chip C's shard
    center WITHIN lane L (and only that lane trips)."""
    par = ParallelConfig(topology="manual", manual_topology=(2, 1, 1))
    cfgs = [_cfg(n=16, parallel=par,
                 output=OutputConfig(check_finite=True)),
            _cfg(n=16, parallel=par)]
    faults.clear()
    faults.install("nan@t=4,field=Ez,chip=1,lane=1")
    try:
        bsim = BatchSimulation(cfgs)
        bsim.advance(4)
        bsim.advance(4)
    finally:
        faults.clear()
    assert bsim.lane_finite == [True, False]
    # the injected cell sat in chip 1's x-half of lane 1 (x >= 8 for
    # the (2,1,1) split of a 16-cell axis) — lane 0 untouched
    assert np.isfinite(bsim.lane_field(0, "Ez")).all()
    bad = np.argwhere(~np.isfinite(bsim.lane_field(1, "Ez")))
    assert len(bad) > 0 and bad[:, 0].min() >= 8


def test_verify_final_lanes_catches_boundary_damage():
    """A NaN landing at the FINAL chunk boundary (after the last
    in-graph measurement) must not read healthy: the end-of-run
    host sweep flips the lane's flag (the CLI calls it before
    printing verdicts)."""
    cfgs = [_cfg(output=OutputConfig(check_finite=True)), _cfg()]
    faults.clear()
    faults.install("nan@t=8,field=Ez,lane=1")   # fires at t=8 = END
    try:
        bsim = BatchSimulation(cfgs)
        bsim.run(8)
    finally:
        faults.clear()
    assert bsim.lane_finite == [True, True]   # in-graph never saw it
    bsim.verify_final_lanes()
    assert bsim.lane_finite == [True, False]
    assert bsim.lane_first_unhealthy_t[1] == 8
    # and the documented service API (Simulation.run_batch) runs the
    # sweep itself — LIBRARY callers get the honest verdict too, not
    # just the CLI
    faults.clear()
    faults.install("nan@t=8,field=Ez,lane=0")
    try:
        b2 = Simulation.run_batch([_cfg(), _cfg()])
    finally:
        faults.clear()
    assert b2.lane_finite == [False, True]


# -------------------------------------------------------------------------
# round 16: lane-capable packed dispatch (batched execution at packed-
# kernel speed) — CPU interpret, bit-for-bit vs solo PACKED runs
# -------------------------------------------------------------------------

def _pcfg(amp=1.0, **kw):
    """Packed-eligible lane config: use_pallas=True rides the Pallas
    interpret path on CPU, so parity can be asserted bit-for-bit
    against a SOLO packed run (same kernel, same rounding)."""
    return _cfg(amp=amp, use_pallas=True, **kw)


def _solo_packed(cfg, steps):
    sim = Simulation(cfg)
    sim.advance(steps)
    return sim


@pytest.mark.parametrize("steps", [8, 7])
def test_batch_packed_parity_bit_identical(steps):
    """THE tentpole acceptance: 3 amplitude-divergent lanes dispatch
    the lane-capable PACKED kernel (batch_fallback None) under ONE
    compiled executable, each lane bit-identical to its solo packed
    run — even AND odd horizons (the tb tail steps batch too)."""
    cfgs = [_pcfg(amp=a) for a in (1.0, 2.0, 0.5)]
    s0 = exec_cache.stats()
    bsim = BatchSimulation(cfgs)
    assert bsim.batch_fallback is None
    assert bsim.step_kind.startswith("pallas_packed")
    bsim.advance(steps)
    s1 = exec_cache.stats()
    assert s1["traces"] - s0["traces"] == 1, \
        "B lanes must cost exactly one trace"
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _solo_packed(cfg, steps))


def test_batch_packed_material_grid_lanes():
    """Per-lane eps GRIDS are traced operands: sphere-value-divergent
    lanes stay in lane-capable scope (no scalar_coeff_divergence) and
    match their solo packed runs bit for bit."""
    def sphere(v):
        return MaterialsConfig(eps_sphere=SphereConfig(
            enabled=True, center=(6.0, 6.0, 6.0), radius=3.0, value=v))
    cfgs = [_pcfg(materials=sphere(2.0)), _pcfg(materials=sphere(4.0))]
    bsim = BatchSimulation(cfgs)
    assert bsim.batch_fallback is None
    assert bsim.step_kind.startswith("pallas_packed")
    bsim.advance(8)
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _solo_packed(cfg, 8))


def test_batch_packed_scalar_divergence_falls_back_named(tmp_path):
    """Scalar-eps-divergent lanes are NOT lane-capable (the packed
    kernel bakes scalar coefficients): the batch falls back to the
    vmap-jnp path with the machine-readable token in BOTH the
    BatchSimulation attribute and the run_start telemetry record —
    and still matches sequential jnp runs bit for bit."""
    path = tmp_path / "t.jsonl"
    cfgs = [_pcfg(eps=1.0,
                  output=OutputConfig(telemetry_path=str(path))),
            _pcfg(eps=2.0)]
    bsim = BatchSimulation(cfgs)
    try:
        assert bsim.batch_fallback == \
            "batch_unsupported:scalar_coeff_divergence"
        assert bsim.step_kind == "jnp"
        bsim.advance(8)
    finally:
        bsim.close()
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _sequential(cfg, 8))
    recs = telemetry.read_jsonl(str(path))
    start = next(r for r in recs if r["type"] == "run_start")
    assert start["batch_fallback"] == \
        "batch_unsupported:scalar_coeff_divergence"


def test_batch_packed_lane_capable_run_start_has_no_token(tmp_path):
    """The complement: a lane-capable batch's run_start carries NO
    batch_fallback key (absent, not null — RECORD_OPTIONAL)."""
    path = tmp_path / "t.jsonl"
    cfgs = [_pcfg(amp=1.0,
                  output=OutputConfig(telemetry_path=str(path))),
            _pcfg(amp=2.0)]
    bsim = BatchSimulation(cfgs)
    try:
        assert bsim.batch_fallback is None
        bsim.advance(4)
    finally:
        bsim.close()
    start = next(r for r in telemetry.read_jsonl(str(path))
                 if r["type"] == "run_start")
    assert "batch_fallback" not in start


def test_batch_packed_nan_trips_only_its_lane():
    """Lane-NaN isolation holds ON THE PACKED PATH: the stacked packed
    carry's health counters unpack per lane in-graph — lane 1's NaN
    never flags (or perturbs) lanes 0/2."""
    cfgs = [_pcfg(output=OutputConfig(check_finite=True)),
            _pcfg(), _pcfg()]
    faults.clear()
    faults.install("nan@t=4,field=Ez,lane=1")
    try:
        bsim = BatchSimulation(cfgs)
        assert bsim.batch_fallback is None
        assert bsim.step_kind.startswith("pallas_packed")
        bsim.advance(4)
        bsim.advance(4)
    finally:
        faults.clear()
    assert bsim.lane_finite == [True, False, True]
    assert bsim.lane_first_unhealthy_t == [None, 8, None]
    clean = _solo_packed(_pcfg(), 8)
    _assert_lane_equal(bsim, 0, clean)
    _assert_lane_equal(bsim, 2, clean)
    assert not np.isfinite(bsim.lane_field(1, "Ez")).all()


def test_batch_vmem_lanes_ladder_downgrade(tmp_path):
    """The lanes ladder: a (simulated) VMEM compile failure of the
    lane-capable executable walks Simulation._VMEM_LADDER_MB rebuilds
    and, when every packed rung is exhausted, lands on the vmap-jnp
    runner with ``batch_unsupported:vmem_exhausted`` + a structured
    ladder_downgrade event — and the run completes bit-identical to
    sequential jnp runs (the live carry was routed old-unpack ->
    new-pack)."""
    path = tmp_path / "t.jsonl"
    cfgs = [_pcfg(amp=1.0,
                  output=OutputConfig(telemetry_path=str(path))),
            _pcfg(amp=2.0)]
    bsim = BatchSimulation(cfgs)
    assert bsim._packed and bsim.batch_fallback is None
    try:
        for _ in range(len(Simulation._VMEM_LADDER_MB) + 1):
            if not bsim._packed:
                break
            bsim._vmem_fallback(
                RuntimeError("RESOURCE_EXHAUSTED: mosaic vmem"))
        assert not bsim._packed
        assert bsim.batch_fallback == \
            "batch_unsupported:vmem_exhausted"
        assert bsim.step_kind == "jnp"
        bsim.advance(8)
    finally:
        bsim.close()
    for lane, cfg in enumerate(cfgs):
        _assert_lane_equal(bsim, lane, _sequential(cfg, 8))
    evs = [r for r in telemetry.read_jsonl(str(path))
           if r["type"] == "ladder_downgrade"]
    assert evs and evs[-1]["new_budget_mb"] is None   # the jnp rung
    # a non-packed batch never enters the ladder: re-raise, not loop
    with pytest.raises(RuntimeError, match="boom"):
        bsim._vmem_fallback(RuntimeError("boom"))


def test_batch_packed_sharded_one_halo_exchange():
    """Sharded (2,2,2) batch ON THE PACKED KIND: per-lane bit parity
    vs the sharded solo packed run AND the compiled module's
    collective-permute count equals the solo module's — the lanes
    share ONE halo exchange per step at packed-kernel cost."""
    par = ParallelConfig(topology="manual", manual_topology=(2, 2, 2))
    cfgs = [_cfg(n=16, amp=a, pml=PmlConfig(size=(2, 2, 2)),
                 parallel=par, use_pallas=True) for a in (1.0, 2.0)]
    bsim = BatchSimulation(cfgs)
    assert bsim.batch_fallback is None
    assert bsim.step_kind.startswith("pallas_packed")
    bsim.advance(8)
    for lane, cfg in enumerate(cfgs):
        sim = _solo_packed(cfg, 8)
        for comp in ("Ez", "Hy"):
            assert np.array_equal(np.asarray(sim.field(comp)),
                                  bsim.lane_field(lane, comp))
    solo = Simulation(cfgs[0])
    solo.advance(8)
    n_batch = _count_collective_permutes(bsim._compiled[8])
    n_solo = _count_collective_permutes(solo._compiled[8])
    assert n_batch > 0
    assert n_batch == n_solo, \
        f"batched packed module has {n_batch} collective-permutes " \
        f"vs solo's {n_solo} — lanes must share the exchange"


def test_batch_exec_key_distinct_per_width():
    """ExecKey carries the batch width: a 2-lane and a 3-lane batch of
    the same scenario, and the solo run, all compile under DISTINCT
    keys (a cached solo executable can never serve a batch, nor one
    width another)."""
    b2 = BatchSimulation([_pcfg(amp=1.0), _pcfg(amp=2.0)])
    b3 = BatchSimulation([_pcfg(amp=1.0), _pcfg(amp=2.0),
                          _pcfg(amp=0.5)])
    k2, k3 = b2.exec_key(8), b3.exec_key(8)
    assert k2.batch == 2 and k3.batch == 3
    assert k2 != k3
    solo = Simulation(_pcfg())
    ks = solo.exec_key(8)
    assert ks.batch == 0
    assert ks != k2
