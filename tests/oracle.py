"""Independent pure-numpy FDTD oracle for cross-checking the JAX solver.

Deliberately written in a different style (explicit slice indexing, float64
throughout, per-step python loop) so that shared indexing/sign bugs with the
production kernels are unlikely. Implements the reference physics oracle
role of the exact-solution callbacks (SURVEY.md §4: "the physics itself is
the oracle").

Conventions matched to the production solver:
  * zero ghost values outside the grid (PEC-backed),
  * tangential E forced to 0 on the walls of active axes,
  * soft point source adds A*wf((t+1/2) dt) into the curl accumulator,
  * E update first (uses H^{n+1/2}), then H.
"""

import math

import numpy as np

EPS0 = 8.8541878128e-12
MU0 = 1.25663706212e-6
C0 = 299792458.0


def wf_sin(t, omega):
    period = 2.0 * math.pi / omega
    r = min(max(t / (2.0 * period), 0.0), 1.0)
    r = r * r * (3.0 - 2.0 * r)
    return r * math.sin(omega * t)


def run_tmz(n, steps, dx, dt, omega, src, amp=1.0):
    """2D TMz vacuum, soft Ez point source at `src`=(i,j). Returns Ez,Hx,Hy."""
    ez = np.zeros((n, n))
    hx = np.zeros((n, n))
    hy = np.zeros((n, n))
    cb = dt / EPS0
    db = dt / MU0
    for t in range(steps):
        curl = np.zeros_like(ez)
        curl += hy / dx
        curl[1:, :] -= hy[:-1, :] / dx
        curl -= hx / dx
        curl[:, 1:] += hx[:, :-1] / dx
        curl[src] += amp * wf_sin((t + 0.5) * dt, omega)
        ez = ez + cb * curl
        ez[0, :] = 0.0
        ez[-1, :] = 0.0
        ez[:, 0] = 0.0
        ez[:, -1] = 0.0
        # Hx -= db * dEz/dy ; Hy += db * dEz/dx  (forward differences)
        dey = np.zeros_like(ez)
        dey[:, :-1] = (ez[:, 1:] - ez[:, :-1]) / dx
        dey[:, -1] = (0.0 - ez[:, -1]) / dx
        dex = np.zeros_like(ez)
        dex[:-1, :] = (ez[1:, :] - ez[:-1, :]) / dx
        dex[-1, :] = (0.0 - ez[-1, :]) / dx
        hx = hx - db * dey
        hy = hy + db * dex
    return ez, hx, hy


def run_3d(n, steps, dx, dt, omega, src, amp=1.0):
    """3D vacuum, soft Ez point source. Returns dict of all six fields."""
    shp = (n, n, n)
    F = {k: np.zeros(shp) for k in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz")}
    cb = dt / EPS0
    db = dt / MU0

    def bdiff(f, ax):
        out = f.copy()
        sl = [slice(None)] * 3
        sr = [slice(None)] * 3
        sl[ax] = slice(1, None)
        sr[ax] = slice(None, -1)
        out[tuple(sl)] -= f[tuple(sr)]
        return out / dx

    def fdiff(f, ax):
        out = -f.copy()
        sl = [slice(None)] * 3
        sr = [slice(None)] * 3
        sl[ax] = slice(None, -1)
        sr[ax] = slice(1, None)
        out[tuple(sl)] += f[tuple(sr)]
        return out / dx

    def pec(f, comp_axis):
        for a in range(3):
            if a == comp_axis:
                continue
            sl0 = [slice(None)] * 3
            sl1 = [slice(None)] * 3
            sl0[a] = 0
            sl1[a] = -1
            f[tuple(sl0)] = 0.0
            f[tuple(sl1)] = 0.0

    for t in range(steps):
        cex = bdiff(F["Hz"], 1) - bdiff(F["Hy"], 2)
        cey = bdiff(F["Hx"], 2) - bdiff(F["Hz"], 0)
        cez = bdiff(F["Hy"], 0) - bdiff(F["Hx"], 1)
        cez[src] += amp * wf_sin((t + 0.5) * dt, omega)
        F["Ex"] = F["Ex"] + cb * cex
        F["Ey"] = F["Ey"] + cb * cey
        F["Ez"] = F["Ez"] + cb * cez
        pec(F["Ex"], 0)
        pec(F["Ey"], 1)
        pec(F["Ez"], 2)
        chx = fdiff(F["Ez"], 1) - fdiff(F["Ey"], 2)
        chy = fdiff(F["Ex"], 2) - fdiff(F["Ez"], 0)
        chz = fdiff(F["Ey"], 0) - fdiff(F["Ex"], 1)
        F["Hx"] = F["Hx"] - db * chx
        F["Hy"] = F["Hy"] - db * chy
        F["Hz"] = F["Hz"] - db * chz
    return F
