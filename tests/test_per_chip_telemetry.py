"""Per-chip telemetry lane (schema v4, ISSUE 7 tentpole).

The fused health readback gains an optional UN-psummed per-chip
counter tuple (tiny all_gathered scalars on the SAME single readback);
the sink records them as v4 ``per_chip`` records plus an ``imbalance``
summary (max/mean ratio, argmax straggler chip). Asserted on the
8-device virtual CPU mesh; plus the v1-v4 fixture-corpus round-trip.
"""

import glob
import json
import os

import numpy as np
import pytest

import jax

from fdtd3d_tpu import telemetry
from fdtd3d_tpu.config import (OutputConfig, ParallelConfig,
                               PmlConfig, PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures")


def _cfg(tmp_path, n_devices=8, per_chip=True):
    return SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=4, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(2, 2, 2)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(8, 8, 8)),
        parallel=ParallelConfig(topology="auto", n_devices=n_devices)
        if n_devices > 1 else ParallelConfig(),
        output=OutputConfig(telemetry_path=str(tmp_path / "t.jsonl"),
                            per_chip_telemetry=per_chip))


def test_per_chip_records_on_mesh(tmp_path):
    cfg = _cfg(tmp_path)
    sim = Simulation(cfg, devices=jax.devices()[:8])
    assert sim.mesh is not None
    sim.advance(2)
    sim.advance(2)
    sim.close()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    chunks = [r for r in recs if r["type"] == "chunk"]
    pcs = [r for r in recs if r["type"] == "per_chip"]
    imbs = [r for r in recs if r["type"] == "imbalance"]
    assert len(pcs) == len(chunks) == 2
    assert len(imbs) == 2
    pc = pcs[-1]
    assert pc["v"] == telemetry.SCHEMA_VERSION and pc["n_chips"] == 8
    assert set(pc["counters"]) == set(telemetry.PER_CHIP_KEYS)
    for vec in pc["counters"].values():
        assert len(vec) == 8
    # the un-psummed per-chip energies sum to the global counter, and
    # the per-chip max_e maxes to it (the same reduction, split open)
    chunk = chunks[-1]
    assert sum(pc["counters"]["energy"]) == \
        pytest.approx(chunk["energy"], rel=1e-5)
    assert max(pc["counters"]["max_e"]) == \
        pytest.approx(chunk["max_e"], rel=1e-6)
    # imbalance summarizes that vector: point source in one shard ->
    # a real straggler chip with ratio > 1
    imb = imbs[-1]
    assert imb["n_chips"] == 8
    assert imb["argmax"] == int(np.argmax(pc["counters"]["energy"]))
    assert imb["ratio"] is not None and imb["ratio"] > 1.0
    assert imb["max"] == pytest.approx(max(pc["counters"]["energy"]))


def test_per_chip_same_single_readback(tmp_path, monkeypatch):
    """The lane rides the existing one-readback budget: enabling it
    must not add device_get calls."""
    calls = []
    orig = jax.device_get

    def counting(x):
        calls.append(1)
        return orig(x)

    cfg = _cfg(tmp_path)
    sim = Simulation(cfg, devices=jax.devices()[:8])
    monkeypatch.setattr(jax, "device_get", counting)
    sim.advance(2)
    assert sum(calls) == 1
    sim.close()


def test_per_chip_unsharded_degenerates(tmp_path):
    """A single-device run still writes per_chip records (length-1
    vectors, one shape for consumers) but no imbalance record —
    nothing to compare."""
    cfg = _cfg(tmp_path, n_devices=1)
    sim = Simulation(cfg)
    sim.advance(2)
    sim.close()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    pcs = [r for r in recs if r["type"] == "per_chip"]
    assert pcs and pcs[0]["n_chips"] == 1
    assert all(len(v) == 1 for v in pcs[0]["counters"].values())
    assert not [r for r in recs if r["type"] == "imbalance"]


def test_per_chip_off_by_default(tmp_path):
    cfg = _cfg(tmp_path, per_chip=False)
    sim = Simulation(cfg, devices=jax.devices()[:8])
    sim.advance(2)
    sim.close()
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    assert not [r for r in recs
                if r["type"] in ("per_chip", "imbalance")]


def test_schema_v4_validation_rules():
    pc = {"chunk": 1, "t": 8, "n_chips": 2,
          "counters": {"energy": [1.0, 2.0]}}
    imb = {"chunk": 1, "t": 8, "metric": "energy", "max": 2.0,
           "mean": 1.5, "ratio": 1.333, "argmax": 1, "n_chips": 2}
    telemetry.validate_record({"v": 4, "type": "per_chip", **pc})
    telemetry.validate_record({"v": 4, "type": "imbalance", **imb})
    # the v4 types are unknown to every older version
    for v in (1, 2, 3):
        with pytest.raises(ValueError, match="unknown record type"):
            telemetry.validate_record({"v": v, "type": "per_chip",
                                       **pc})
        with pytest.raises(ValueError, match="unknown record type"):
            telemetry.validate_record({"v": v, "type": "imbalance",
                                       **imb})
    with pytest.raises(ValueError, match="missing"):
        telemetry.validate_record({"v": 4, "type": "per_chip",
                                   "chunk": 1, "t": 8})
    # a degenerate imbalance (zero mean) records ratio null
    telemetry.validate_record({"v": 4, "type": "imbalance",
                               **dict(imb, ratio=None)})


def test_imbalance_summary_helper():
    s = telemetry.imbalance_summary(
        {"energy": [1.0, 1.0, 2.0, 0.0]})
    assert s["max"] == 2.0 and s["argmax"] == 2 and s["n_chips"] == 4
    assert s["ratio"] == pytest.approx(2.0 / 1.0)
    assert telemetry.imbalance_summary({"energy": [1.0]}) is None
    assert telemetry.imbalance_summary({}) is None
    # a NON-FINITE chip is the worst straggler there is: it is named
    # as argmax (ratio null, nonfinite_chips listed) — never dropped
    # in favor of a healthy chip (review finding, round 10)
    s2 = telemetry.imbalance_summary(
        {"energy": [1.0, float("nan"), 3.0]})
    assert s2["argmax"] == 1 and s2["nonfinite_chips"] == [1]
    assert s2["ratio"] is None and s2["max"] == 3.0


def test_sink_scrubs_nested_nonfinite(tmp_path):
    """A diverging chip's NaN counter must not emit a NaN literal
    (not JSON) inside the nested per_chip vectors."""
    sink = telemetry.TelemetrySink(str(tmp_path / "s.jsonl"))
    sink.emit("per_chip", chunk=1, t=8, n_chips=2,
              counters={"energy": [1.0, float("nan")]})
    sink._fh.close()
    sink._fh = None
    line = (tmp_path / "s.jsonl").read_text().strip()
    rec = json.loads(line)  # would raise on a bare NaN literal
    assert rec["counters"]["energy"] == [1.0, None]


def test_fixture_corpus_round_trips_v1_to_v8():
    """Satellite acceptance: every checked-in telemetry JSONL fixture
    still validates, and the corpus spans schema v1..v7 so no version
    can silently rot out of the read path."""
    paths = sorted(glob.glob(os.path.join(FIX, "*.jsonl")))
    assert paths, "no JSONL fixtures found"
    versions = set()
    for path in paths:
        for rec in telemetry.read_jsonl(path):  # validates each record
            versions.add(rec["v"])
            # round-trip: re-serialized records validate too
            telemetry.validate_record(json.loads(json.dumps(rec)))
    assert versions >= set(telemetry.READ_VERSIONS), versions
    # and the v4 file specifically carries the new record types
    types = {r["type"] for r in telemetry.read_jsonl(
        os.path.join(FIX, "telemetry_v4.jsonl"))}
    assert {"per_chip", "imbalance", "retry", "rollback",
            "degrade"} <= types
    # the v5 file carries the topology-elastic types + chip stamps
    v5 = telemetry.read_jsonl(os.path.join(FIX, "telemetry_v5.jsonl"))
    assert {"topology_change"} <= {r["type"] for r in v5}
    assert any(r.get("chip") is not None for r in v5
               if r["type"] == "rollback")
    # the v6 file carries the batched executor's per-lane rows + the
    # compile-amortization keys (run_start aot_cache snapshot, run_end
    # compile_ms), with a non-finite lane's counters as null
    v6 = telemetry.read_jsonl(os.path.join(FIX, "telemetry_v6.jsonl"))
    lanes = [r for r in v6 if r["type"] == "batch_lane"]
    assert lanes and any(not r["finite"] and r["max_e"] is None
                         for r in lanes)
    start = next(r for r in v6 if r["type"] == "run_start")
    assert isinstance(start["aot_cache"], dict) and start["batch"] == 3
    end = next(r for r in v6 if r["type"] == "run_end")
    assert isinstance(end["compile_ms"], (int, float))
    # the v7 file carries the SLO alert records (rule id + firing
    # window) and the run-registry join stamp on run_start
    v7 = telemetry.read_jsonl(os.path.join(FIX, "telemetry_v7.jsonl"))
    alerts = [r for r in v7 if r["type"] == "alert"]
    assert alerts and all(
        isinstance(a["rule"], str) and a["t_end"] >= a["t_start"]
        for a in alerts)
    start7 = next(r for r in v7 if r["type"] == "run_start")
    assert isinstance(start7["run_id"], str)
    # the registry fixture's row types validate under the SAME schema
    # (runs.jsonl shares the telemetry validator — by construction)
    reg = telemetry.read_jsonl(os.path.join(FIX, "registry_v7.jsonl"))
    assert {r["type"] for r in reg} == {"run_begin", "run_final"}
    assert any(r.get("unhealthy_lanes") for r in reg
               if r["type"] == "run_final")
    # alerts older than v7 must reject (version-gated record type)
    import pytest
    with pytest.raises(ValueError, match="unknown record type"):
        telemetry.validate_record(dict(alerts[0], v=6))
    # the v8 queue-journal fixture (fdtd3d_tpu/jobqueue.py writers):
    # submit + state rows validate, the preempted->queued->running->
    # completed chain is present, and the job row types are
    # version-gated to v8
    v8 = telemetry.read_jsonl(os.path.join(FIX, "queue_v8.jsonl"))
    assert {r["type"] for r in v8} == {"job_submit", "job_state"}
    resumed = [r for r in v8 if r["job_id"] == "j-00002-cc33"]
    assert [r["status"] for r in resumed] == \
        ["queued", "running", "preempted", "queued", "running",
         "completed"]
    assert any(isinstance(r.get("wait_s"), float) for r in v8
               if r["type"] == "job_state")
    with pytest.raises(ValueError, match="unknown record type"):
        telemetry.validate_record(dict(v8[0], v=7))
