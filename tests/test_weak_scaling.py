"""Weak-scaling harness smoke + planner invariants on the CPU mesh.

The 8-device virtual mesh cannot measure bandwidth, but it CAN pin the
planner's accounting (VERDICT weak-4): under weak scaling — constant
per-device tile, growing mesh — the per-chip state and the per-chip
halo-exchange traffic must be CONSTANT once the set of sharded axes
stops changing (each sharded axis contributes 2 x planes x tile^2 x
itemsize regardless of how many shards it has). plan() is pure host
math, so the invariant is assertable up to pod scale without devices.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import numpy as np  # noqa: E402
from weak_scaling import run_point  # noqa: E402


def test_weak_scaling_points_run():
    r1 = run_point(1, tile=16, steps=4)
    r8 = run_point(8, tile=16, steps=4)
    assert r1["n_devices"] == 1 and r8["n_devices"] == 8
    assert r8["global_size"] != r1["global_size"], "workload must grow"
    assert r8["mcells_per_s"] > 0 and r1["mcells_per_s"] > 0
    # per-device local volume is constant (weak scaling)
    v1 = np.prod(r1["global_size"]) / r1["n_devices"]
    v8 = np.prod(r8["global_size"]) / r8["n_devices"]
    assert v1 == v8


def _plan_for(tile: int, n_devices: int):
    from fdtd3d_tpu.config import ParallelConfig, PmlConfig, SimConfig
    from fdtd3d_tpu.parallel.mesh import choose_topology
    from fdtd3d_tpu.plan import plan

    # same sizing rule tools/weak_scaling.run_point uses
    probe = choose_topology(n_devices, (tile * n_devices,) * 3, (0, 1, 2))
    size = tuple(tile * p for p in probe)
    cfg = SimConfig(
        scheme="3D", size=size, time_steps=4, dx=1e-3,
        courant_factor=0.5, wavelength=32e-3,
        pml=PmlConfig(size=(min(10, tile // 4),) * 3),
        parallel=ParallelConfig(topology="auto", n_devices=n_devices))
    return plan(cfg, n_devices=n_devices)


def test_halo_traffic_invariant_under_weak_scaling():
    """The ledger comm model's per-chip halo bytes/step (the ONE
    source of truth the tools quote: costs.halo_bytes_per_chip ->
    plan.py) must be constant under weak scaling once all three axes
    shard (8 -> 64 -> 512 chips), agree with plan() row-for-row, AND
    match the independent hand curl-term oracle — kept in the TEST
    precisely so plan() is never verified against itself
    (VERDICT weak-4)."""
    from fdtd3d_tpu.costs import halo_bytes_per_chip
    from fdtd3d_tpu.config import ParallelConfig, PmlConfig, SimConfig
    from fdtd3d_tpu.parallel.mesh import choose_topology

    tile = 16
    plans = {n: _plan_for(tile, n) for n in (8, 64, 512)}
    # all-axes-sharded topologies: identical local shape and halo bytes
    for n, p in plans.items():
        assert all(t > 1 for t in p.topology), (n, p.topology)
        assert p.local_shape == (tile, tile, tile)
    halos = {n: p.halo_bytes_per_step for n, p in plans.items()}
    assert len(set(halos.values())) == 1, halos

    # independent magnitude oracle (kept on purpose: the tools quote
    # ONE model, but the test must not verify plan() against itself):
    # per sharded axis, 2 directions x curl-term planes x tile^2 x 4 B
    from fdtd3d_tpu.plan import _halo_planes
    from fdtd3d_tpu.solver import build_static
    mode = build_static(SimConfig(scheme="3D", size=(16, 16, 16),
                                  time_steps=1)).mode
    expect = sum(2 * _halo_planes(mode, a) * tile * tile * 4
                 for a in range(3))
    assert halos[512] == expect, (halos[512], expect)

    # the planner's number IS the ledger comm model's number, per
    # topology (single source of truth — what weak_scaling.py rows and
    # the ledger comm lane both quote)
    for n, p in plans.items():
        probe = choose_topology(n, (tile * n,) * 3, (0, 1, 2))
        size = tuple(tile * t for t in probe)
        cfg = SimConfig(
            scheme="3D", size=size, time_steps=4, dx=1e-3,
            courant_factor=0.5, wavelength=32e-3,
            pml=PmlConfig(size=(min(10, tile // 4),) * 3),
            parallel=ParallelConfig(topology="auto", n_devices=n))
        assert halo_bytes_per_chip(cfg, p.topology) == \
            p.halo_bytes_per_step, n
    # and the per-axis breakdown sums to the total
    bya = plans[8].halo_by_axis
    assert sum(r["bytes_per_step"] for r in bya.values()) == halos[8]
    assert all(r["bytes_per_step"] == 2 * r["bytes_per_neighbor_per_step"]
               for r in bya.values())

    # per-chip state is constant under weak scaling too
    hbm = {n: p.hbm_per_chip for n, p in plans.items()}
    assert len(set(hbm.values())) == 1, hbm


def test_tb_halo_model_invariant_and_matches_ledger():
    """ISSUE-10 satellite: the temporal-blocked kernel's depth-2 halo
    model (plan.halo_bytes_per_step_tb — two ghost-plane generations
    per neighbor per pass = (ne+nh) component planes per axis per
    STEP) is (a) invariant 8 -> 512 chips under weak scaling, (b) the
    number the ledger's sharded tb trace equals to the byte, and
    (c) carried by the weak-scaling harness rows."""
    from fdtd3d_tpu import costs
    from fdtd3d_tpu.costs import halo_bytes_per_chip

    tile = 16
    plans = {n: _plan_for(tile, n) for n in (8, 64, 512)}
    halos_tb = {n: p.halo_bytes_per_step_tb for n, p in plans.items()}
    assert len(set(halos_tb.values())) == 1, halos_tb
    # independent magnitude oracle: per sharded axis, send+recv x
    # (ne + nh) component planes x tile^2 x 4 B per STEP — the full
    # stacks of BOTH generations per pass, halved per step
    expect = 3 * 2 * 6 * tile * tile * 4
    assert halos_tb[512] == expect, (halos_tb[512], expect)
    # per-axis tb breakdown sums to the total
    bya = plans[8].halo_by_axis_tb
    assert sum(r["bytes_per_step"] for r in bya.values()) == halos_tb[8]

    # (b) the ledger's sharded tb trace == this model, per topology
    cfg = costs.config_for_kind("pallas_packed_tb", n=16, pml=2)
    led = costs.chunk_ledger(cfg, n_steps=8, kind="pallas_packed_tb",
                             topology=(2, 2, 2))
    comm = led["comm"]
    from fdtd3d_tpu.plan import plan_for_topology
    p222 = plan_for_topology(cfg, (2, 2, 2))
    assert comm["per_step"]["ppermute_bytes_per_chip"] == \
        p222.halo_bytes_per_step_tb
    assert comm["plan"]["halo_bytes_per_chip_per_step"] == \
        p222.halo_bytes_per_step_tb
    assert halo_bytes_per_chip(cfg, (2, 2, 2),
                               step_kind="pallas_packed_tb") == \
        p222.halo_bytes_per_step_tb

    # (c) the harness row carries it
    r8 = run_point(8, tile=16, steps=4)
    p8 = _plan_for(16, 8)
    assert r8["halo_bytes_per_chip_per_step_tb"] == \
        p8.halo_bytes_per_step_tb


def test_plan_matches_live_run_topology():
    """The planner's chosen topology agrees with what the live 8-device
    run resolves (the accounting is about THAT decomposition), and the
    harness row carries the ledger comm model's halo number for it."""
    r8 = run_point(8, tile=16, steps=4)
    p8 = _plan_for(16, 8)
    assert tuple(r8["topology"]) == p8.topology
    assert r8["halo_bytes_per_chip_per_step"] == p8.halo_bytes_per_step
