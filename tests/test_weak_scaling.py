"""Weak-scaling harness smoke on the 8-device virtual CPU mesh."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from weak_scaling import run_point  # noqa: E402


def test_weak_scaling_points_run():
    r1 = run_point(1, tile=16, steps=4)
    r8 = run_point(8, tile=16, steps=4)
    assert r1["n_devices"] == 1 and r8["n_devices"] == 8
    assert r8["global_size"] != r1["global_size"], "workload must grow"
    assert r8["mcells_per_s"] > 0 and r1["mcells_per_s"] > 0
    # per-device local volume is constant (weak scaling)
    import numpy as np
    v1 = np.prod(r1["global_size"]) / r1["n_devices"]
    v8 = np.prod(r8["global_size"]) / r8["n_devices"]
    assert v1 == v8
