"""Exact-solution oracle tests + NTFF dipole-pattern test.

The cavity eigenmode tests are the strongest oracle in the suite: the
initialized mode shape is an exact eigenfunction of the discrete Yee
operator, so in float64 the solver must track the analytic time evolution
to ~1e-12 over hundreds of steps. Any stencil/coefficient/wall bug fails
this loudly. (Reference analog: polynomial exact-solution callbacks with
machine-eps norms, SURVEY.md §4.)
"""

import math

import numpy as np
import pytest

from fdtd3d_tpu import diag, exact, physics
from fdtd3d_tpu.config import PointSourceConfig, SimConfig
from fdtd3d_tpu.sim import Simulation


def test_cavity_mode_2d_exact_evolution_f64():
    n, steps = 33, 300
    cfg = SimConfig(scheme="2D_TMz", size=(n, n, 1), time_steps=steps,
                    dx=1e-3, courant_factor=0.6, wavelength=10e-3,
                    dtype="float64")
    sim = Simulation(cfg)
    shape, omega = exact.cavity_mode_tmz((n, n), 2, 3, cfg.dx, cfg.dt)
    sim.set_field("Ez", shape[:, :, None])
    sim.run()
    expected = exact.cavity_expectation(shape, omega, cfg.dt, steps)
    got = sim.field("Ez")[:, :, 0]
    err = np.max(np.abs(got - expected))
    assert err < 1e-10, f"cavity mode drifted: {err:.2e}"


def test_cavity_mode_3d_exact_evolution_f64():
    """z-invariant (p=0) mode: only Ez active; Hz/Ex stay exactly zero."""
    n, nz, steps = 21, 8, 200
    cfg = SimConfig(scheme="3D", size=(n, n, nz), time_steps=steps,
                    dx=1e-3, courant_factor=0.5, wavelength=10e-3,
                    dtype="float64")
    sim = Simulation(cfg)
    mode, omega = exact.cavity_mode_3d((n, n, nz), (2, 1, 0), cfg.dx,
                                       cfg.dt)
    assert set(mode) == {"Ez"}
    sim.set_field("Ez", mode["Ez"])
    sim.run()
    expected = exact.cavity_expectation(mode["Ez"], omega, cfg.dt, steps)
    err = np.max(np.abs(sim.field("Ez") - expected))
    assert err < 1e-10, f"3D cavity mode drifted: {err:.2e}"
    # inactive-in-this-mode components stayed exactly zero
    assert np.abs(sim.field("Hz")).max() == 0.0
    assert np.abs(sim.field("Ex")).max() == 0.0


def test_cavity_mode_3d_full_vector_exact_evolution_f64():
    """All of (m, n, p) nonzero: every E component carries the mode and
    must track the discrete-dispersion evolution to machine precision —
    the strongest whole-solver oracle (all six components, all three
    curl-term axes)."""
    nx, ny, nz, steps = 17, 21, 13, 150
    cfg = SimConfig(scheme="3D", size=(nx, ny, nz), time_steps=steps,
                    dx=1e-3, courant_factor=0.5, wavelength=10e-3,
                    dtype="float64")
    sim = Simulation(cfg)
    mode, omega = exact.cavity_mode_3d((nx, ny, nz), (2, 3, 1), cfg.dx,
                                       cfg.dt)
    assert set(mode) == {"Ex", "Ey", "Ez"}
    for comp, shape in mode.items():
        sim.set_field(comp, shape)
    sim.run()
    for comp, shape in mode.items():
        expected = exact.cavity_expectation(shape, omega, cfg.dt, steps)
        err = np.max(np.abs(sim.field(comp) - expected))
        assert err < 1e-10, f"{comp} drifted: {err:.2e}"
    for comp in ("Hx", "Hy", "Hz"):
        assert np.abs(sim.field(comp)).max() > 0.0


def test_discrete_dispersion_matches_tfsf_steady_state():
    """Non-magic Courant factor: interior CW field matches the plane wave
    with the DISCRETE wave number to ~1e-3 (continuum k would miss badly).
    """
    from fdtd3d_tpu.config import PmlConfig, TfsfConfig
    n = 220
    cfg = SimConfig(
        scheme="1D_EzHy", size=(n, 1, 1), time_steps=1200, dx=1e-3,
        courant_factor=0.7, wavelength=20e-3, dtype="float64",
        pml=PmlConfig(size=(10, 0, 0)),  # absorb past the box (PEC would
        tfsf=TfsfConfig(enabled=True,    # re-inject a standing component)
                        margin=(8, 0, 0), angle_teta=90.0,
                        angle_phi=0.0, angle_psi=180.0))
    sim = Simulation(cfg)
    sim.run()
    ez = sim.field("Ez")[:, 0, 0]
    setup = sim.static.tfsf_setup
    x = np.arange(60, 160, dtype=np.float64)
    # steady sine: fit amplitude/phase against the discrete-k ansatz
    k = exact.discrete_k_1d(cfg.omega, cfg.dx, cfg.dt)
    basis = np.stack([np.sin(k * x), np.cos(k * x)], axis=1)
    coef, res, *_ = np.linalg.lstsq(basis, ez[60:160], rcond=None)
    fit = basis @ coef
    err = np.max(np.abs(fit - ez[60:160]))
    amp = math.hypot(*coef)
    assert 0.97 < amp < 1.03, f"amplitude {amp}"
    # 1.5% residual (ramp-spectrum sidebands); the CONTINUUM k would be
    # ~6.6% off over this window, so this bound proves the discrete k.
    assert err < 1.5e-2 * amp, f"discrete-dispersion mismatch {err:.2e}"


def test_ntff_dipole_pattern():
    """A z-directed point current radiates sin^2(theta): check the NTFF
    pattern shape and phi symmetry."""
    from fdtd3d_tpu.config import PmlConfig
    from fdtd3d_tpu.ntff import NtffCollector
    n = 48
    cfg = SimConfig(
        scheme="3D", size=(n, n, n), time_steps=0, dx=1e-3,
        courant_factor=0.5, wavelength=12e-3,
        pml=PmlConfig(size=(8, 8, 8)),  # open boundary: PEC walls would
        point_source=PointSourceConfig(  # turn this into a ringing cavity
            enabled=True, component="Ez", position=(n // 2,) * 3),
    )
    sim = Simulation(cfg)
    sim.advance(300)  # reach steady CW state
    # box symmetric about the source cell (n/2): lo + hi == n.
    col = NtffCollector(sim, frequency=physics.C0 / cfg.wavelength,
                        box=((12, 12, 12), (n - 12, n - 12, n - 12)))
    period_steps = cfg.wavelength / physics.C0 / cfg.dt
    stride = max(1, int(round(period_steps / 16)))
    for _ in range(48):  # ~3 periods, 16 samples each
        sim.advance(stride)
        col.sample()
    p90 = col.directivity_pattern([90.0], [0.0, 90.0, 180.0, 270.0])[0]
    p90d = col.directivity_pattern([90.0], [45.0])[0, 0]
    p45 = col.directivity_pattern([45.0], [0.0])[0, 0]
    p10 = col.directivity_pattern([10.0], [0.0])[0, 0]
    # phi symmetry at the equator: tight along the axes, looser on the
    # cube diagonal (grid + box anisotropy of the 2nd-order surface rule).
    assert p90.max() / p90.min() < 1.2, f"phi asymmetry {p90}"
    assert 0.6 < p90d / p90.mean() < 1.4, f"diagonal {p90d/p90.mean():.2f}"
    # sin^2 shape: D(45)/D(90) ~ 0.5, D(10)/D(90) ~ 0.03. The small
    # 1-2 wavelength box at 12 cells/lambda flattens the lobe somewhat
    # (measured 0.63-0.67); the theta=0 null and monotone falloff are the
    # robust discriminators.
    r45 = p45 / p90.mean()
    r10 = p10 / p90.mean()
    assert 0.35 < r45 < 0.75, f"D(45)/D(90) = {r45:.3f}"
    assert r10 < 0.15, f"D(10)/D(90) = {r10:.3f}"


def test_ntff_cli_black_box(tmp_path):
    """--ntff end-to-end from the CLI: pattern file written, sin^2(theta)
    shape (theta=0/180 nulls, equatorial peak, phi symmetry)."""
    import contextlib
    import io as _io

    from fdtd3d_tpu import cli

    n = 40
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main([
            "--3d", "--same-size", str(n), "--time-steps", "260",
            "--courant-factor", "0.5", "--wavelength", "12e-3",
            "--use-pml", "--pml-size", "7",
            "--point-source", "Ez",
            "--ntff", "--ntff-margin", "3",
            "--ntff-theta-steps", "7", "--ntff-phi-steps", "8",
            "--save-dir", str(tmp_path)])
    assert rc == 0, buf.getvalue()
    path = tmp_path / "ntff_pattern.txt"
    assert path.exists(), buf.getvalue()
    rows = np.loadtxt(path)
    thetas = np.unique(rows[:, 0])
    pattern = {th: rows[rows[:, 0] == th][:, 2] for th in thetas}
    eq = pattern[90.0]
    assert eq.mean() > 0.5, "equatorial lobe missing"
    assert eq.max() / eq.min() < 1.3, "phi asymmetry"
    assert pattern[0.0].max() < 0.15, "theta=0 null missing"
    assert pattern[180.0].max() < 0.15, "theta=180 null missing"
    assert pattern[30.0].mean() < pattern[60.0].mean() < eq.mean(), \
        "pattern not monotone toward the equator"


def test_ntff_cli_explicit_box_matches_margin(tmp_path):
    """--ntff-box-lo/hi override the margin-derived box; an explicit box
    equal to the margin default must reproduce the same pattern file."""
    import contextlib
    import io as _io

    from fdtd3d_tpu import cli

    n = 40
    base = ["--3d", "--same-size", str(n), "--time-steps", "200",
            "--courant-factor", "0.5", "--wavelength", "12e-3",
            "--use-pml", "--pml-size", "7", "--point-source", "Ez",
            "--ntff", "--ntff-theta-steps", "5", "--ntff-phi-steps", "6"]
    outs = []
    # margin 3 -> box lo = 7+3 = 10, hi = 40-1-7-3 = 29
    for extra in (["--ntff-margin", "3"],
                  ["--ntff-box-lo", "10,10,10",
                   "--ntff-box-hi", "29,29,29"]):
        d = tmp_path / extra[0].strip("-").replace("-", "_")
        d.mkdir()
        buf = _io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli.main(base + extra + ["--save-dir", str(d)])
        assert rc == 0, buf.getvalue()
        outs.append(np.loadtxt(d / "ntff_pattern.txt"))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)


@pytest.mark.slow
def test_ntff_sharded_matches_unsharded():
    """NTFF face sampling on a sharded sim (single process): the lazy
    global-index slicing must gather the right planes; pattern equals
    the unsharded run's. Slow lane (tier-1 wall budget): the NTFF path
    is untouched since seed and tier-1 keeps the unsharded pattern +
    CLI tests above."""
    from fdtd3d_tpu.config import ParallelConfig, PmlConfig
    from fdtd3d_tpu.ntff import NtffCollector

    n = 32

    def run(parallel):
        cfg = SimConfig(
            scheme="3D", size=(n, n, n), time_steps=0, dx=1e-3,
            courant_factor=0.5, wavelength=12e-3,
            pml=PmlConfig(size=(6, 6, 6)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(n // 2,) * 3),
            parallel=parallel)
        sim = Simulation(cfg)
        sim.advance(120)
        col = NtffCollector(sim, frequency=physics.C0 / cfg.wavelength,
                            box=((9, 9, 9), (n - 9,) * 3))
        for _ in range(24):
            sim.advance(2)
            col.sample()
        return col.directivity_pattern([45.0, 90.0], [0.0, 90.0])

    ref = run(ParallelConfig())
    shd = run(ParallelConfig(topology="manual", manual_topology=(2, 2, 2)))
    assert np.allclose(shd, ref, rtol=1e-4), f"{shd} vs {ref}"
