"""Attribution toolchain (ISSUE 3): trace_attribution, perf_sentinel,
telemetry_report against checked-in fixtures, telemetry schema v2, and
the device-trace lane's crash-safe/degrade-to-skip wiring.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from fdtd3d_tpu import costs, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -------------------------------------------------------------------------
# telemetry schema v2
# -------------------------------------------------------------------------

def test_run_start_carries_device_kind_and_probe(tmp_path):
    """Satellite: run_start provenance gains device_kind + hbm_gbps
    (BENCH_BEST already carried both; the JSONL now does too)."""
    from fdtd3d_tpu.config import OutputConfig, PmlConfig, SimConfig
    from fdtd3d_tpu.sim import Simulation
    telemetry.set_hbm_probe(612.5)
    try:
        cfg = SimConfig(
            scheme="2D_TMz", size=(16, 16, 1), time_steps=2, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3,
            pml=PmlConfig(size=(3, 3, 0)),
            output=OutputConfig(
                telemetry_path=str(tmp_path / "t.jsonl")))
        sim = Simulation(cfg)
        sim.advance(2)
        sim.close_telemetry()
    finally:
        telemetry.set_hbm_probe(None)
    recs = telemetry.read_jsonl(cfg.output.telemetry_path)
    start = recs[0]
    assert start["v"] == telemetry.SCHEMA_VERSION
    assert isinstance(start["device_kind"], str) and start["device_kind"]
    assert start["hbm_gbps"] == 612.5


def test_schema_v2_validation_rules():
    base = {"wall_time": "t", "git_sha": "s", "jax_version": "j",
            "platform": "cpu"}
    # v1 run_start: valid WITHOUT the v2 keys (old files keep reading)
    telemetry.validate_record({"v": 1, "type": "run_start", **base})
    # v2 run_start REQUIRES them
    with pytest.raises(ValueError, match="device_kind"):
        telemetry.validate_record({"v": 2, "type": "run_start", **base})
    telemetry.validate_record({"v": 2, "type": "run_start", **base,
                               "device_kind": "cpu", "hbm_gbps": None})
    # the attribution record type exists only from v2 on
    att = {"source": "x", "sections": {}, "measured_total_ms": None,
           "coverage_bytes": None}
    telemetry.validate_record({"v": 2, "type": "attribution", **att})
    with pytest.raises(ValueError, match="unknown record type"):
        telemetry.validate_record({"v": 1, "type": "attribution", **att})
    # v3 (round 9), v4 (round 10), v5 (round 11), v6 (round 15),
    # v7 (round 16), v8 (round 18), v9 (round 20, the trace plane),
    # v10 (the health plane) and v11 (the lease plane) are valid
    # versions now — but the v2 required keys still apply
    for v in (3, 4, 5, 6, 7, 8, 9, 10, 11):
        with pytest.raises(ValueError, match="device_kind"):
            telemetry.validate_record({"v": v, "type": "run_start",
                                       **base})
    with pytest.raises(ValueError, match="not in"):
        telemetry.validate_record({"v": 12, "type": "run_start",
                                   **base})


def test_fixture_jsonl_validates_and_reports():
    """Golden smoke for tools/telemetry_report.py on a checked-in
    mixed v1/v2 fixture file."""
    path = os.path.join(FIX, "telemetry_v2.jsonl")
    recs = telemetry.read_jsonl(path)  # validates every record
    tr = _load_tool("telemetry_report")
    runs = tr.split_runs(recs)
    assert len(runs) == 2  # one v2 run, one legacy v1 run
    s = tr.summarize_run(runs[0])
    assert s["provenance"]["device_kind"] == "TPU v5 lite"
    assert s["chunks"] == 4 and s["complete"] is True
    assert s["steps"] == 360
    assert s["mcells_per_s"]["max"] == pytest.approx(7645.0)
    assert s["first_unhealthy_t"] is None
    txt = tr.format_text([s])
    assert "Mcells/s" in txt and "healthy" in txt
    # the report tool end-to-end (subprocess, like an operator runs it)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "telemetry_report.py"), path],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "run 2:" in proc.stdout  # both runs summarized


# -------------------------------------------------------------------------
# trace_attribution
# -------------------------------------------------------------------------

def test_trace_attribution_fixture_golden():
    ta = _load_tool("trace_attribution")
    path = os.path.join(FIX, "fixture.trace.json")
    graph_ms, host_ms = ta.attribute_events(ta._load_events(path))
    # golden sums (µs -> ms); the cpml-nested event attributes to cpml
    # (innermost scope wins, matching the cost ledger's rule)
    assert graph_ms == pytest.approx(
        {"E-update": 0.150, "cpml": 0.030, "H-update": 0.080,
         "packed-kernel": 0.200, "health": 0.010})
    assert host_ms == pytest.approx({"chunk": 1.0, "compile": 0.7})
    with open(os.path.join(FIX, "ledger_ref.json")) as f:
        ledger = json.load(f)
    rec = ta.merge_with_ledger(graph_ms, host_ms, ledger, path)
    telemetry.validate_record(rec)  # a schema-v2 attribution record
    assert rec["measured_total_ms"] == pytest.approx(0.47)
    assert rec["sections"]["E-update"]["measured_frac"] == \
        pytest.approx(0.150 / 0.47, abs=1e-4)
    assert rec["sections"]["E-update"]["modeled_bytes_frac"] == 0.6
    txt = ta.format_text(rec)
    assert "E-update" in txt and "measured" in txt


def test_trace_attribution_cli_and_skip(tmp_path, capsys):
    ta = _load_tool("trace_attribution")
    # clean skip on an empty dir: exit 0, no artifact written
    out = tmp_path / "attr.jsonl"
    rc = ta.main([str(tmp_path), "--out", str(out)])
    assert rc == 0
    assert not out.exists()
    assert "nothing to attribute" in capsys.readouterr().out
    # full CLI on the fixture trace + ledger -> validated JSONL record
    rc = ta.main([os.path.join(FIX, "fixture.trace.json"),
                  "--ledger", os.path.join(FIX, "ledger_ref.json"),
                  "--json", "--out", str(out)])
    assert rc == 0
    line = out.read_text().strip()
    rec = json.loads(line)
    telemetry.validate_record(rec)
    assert rec["ledger_step_kind"] == "pallas_packed"


# -------------------------------------------------------------------------
# perf_sentinel
# -------------------------------------------------------------------------

CUR_OK = {"platform": "tpu", "hbm_probe_gbps": 600.0,
          "pallas_mcells": 7950.0, "jnp_mcells": 1640.0,
          "bf16_mcells": 13850.0, "float32x2_mcells": 1615.0}


def _sentinel():
    return _load_tool("perf_sentinel")


def _best():
    with open(os.path.join(FIX, "bench_best.json")) as f:
        return json.load(f)


def _history():
    return _sentinel().load_history(
        os.path.join(FIX, "bench_history_r*.json"))


def test_sentinel_ok_and_regression():
    ps = _sentinel()
    v = ps.check_artifact(CUR_OK, _best(), _history())
    assert v["status"] == "OK" and not v["regressions"]
    # a >10% f32-packed drop at the SAME window calibration regresses
    bad = dict(CUR_OK, pallas_mcells=7000.0)
    v = ps.check_artifact(bad, _best(), _history())
    assert v["status"] == "REGRESSION"
    assert v["paths"]["f32_packed"]["verdict"] == "REGRESSION"
    assert any("f32_packed" in m for m in v["regressions"])
    # a 9% drop stays inside the threshold
    v = ps.check_artifact(dict(CUR_OK, pallas_mcells=7300.0),
                          _best(), _history())
    assert v["status"] == "OK"


def test_sentinel_window_normalization():
    """A throttled window (probe at half the reference's) must not cry
    wolf: the reference scales down before comparing."""
    ps = _sentinel()
    throttled = dict(CUR_OK, hbm_probe_gbps=300.0, pallas_mcells=4000.0,
                     jnp_mcells=830.0, bf16_mcells=7000.0,
                     float32x2_mcells=820.0)
    v = ps.check_artifact(throttled, _best(), _history())
    assert v["status"] == "OK", v
    # no probe pair at all -> INCONCLUSIVE (warn, never gate)
    blind = dict(CUR_OK, pallas_mcells=4000.0)
    blind.pop("hbm_probe_gbps")
    v = ps.check_artifact(blind, _best(), _history())
    assert v["paths"]["f32_packed"]["verdict"] == "INCONCLUSIVE"
    assert v["status"] == "INCONCLUSIVE" and not v["regressions"]


def test_sentinel_small_grid_is_inconclusive():
    """A window that never passed the 512^3 gate reports its 256^3
    numbers — readback-dominated, up to ~4x under the chip's speed.
    Against a 640^3 reference that is grid amortization, not a code
    regression (bench.py's own f32_note)."""
    ps = _sentinel()
    throttled = dict(CUR_OK, pallas_mcells=2000.0, f32_n=256)
    v = ps.check_artifact(throttled, _best(), _history())
    row = v["paths"]["f32_packed"]
    assert row["verdict"] == "INCONCLUSIVE", row
    assert row["grids"] == [256, 640]
    assert not v["regressions"]
    # same drop AT the reference grid size still regresses
    v = ps.check_artifact(dict(CUR_OK, pallas_mcells=2000.0,
                               f32_n=640), _best(), _history())
    assert v["paths"]["f32_packed"]["verdict"] == "REGRESSION"


def test_sentinel_skips_off_tpu():
    ps = _sentinel()
    v = ps.check_artifact({"platform": "cpu", "jnp_mcells": 5.0},
                          _best(), _history())
    assert v["status"] == "SKIPPED" and not v["regressions"]


def test_sentinel_history_beats_best():
    """float32x2 has no entry in BENCH_BEST; the r* history supplies
    the reference (1620 in the fixture round)."""
    ps = _sentinel()
    v = ps.check_artifact(dict(CUR_OK, float32x2_mcells=1000.0),
                          _best(), _history())
    assert v["paths"]["float32x2"]["reference"] == 1620.0
    assert v["paths"]["float32x2"]["verdict"] == "REGRESSION"


def test_sentinel_tb_paths_registered():
    """Round-8 satellite: the temporal-blocked kernel is a first-class
    sentinel path (f32_packed_tb / bf16_tb), referenced from the r*
    history (the r10 fixture round carries tb keys) and window- and
    grid-normalized like every other path."""
    ps = _sentinel()
    cur = dict(CUR_OK, tb_mcells=15100.0, tb_n=640,
               tb_bf16_mcells=26100.0, tb_bf16_n=768)
    v = ps.check_artifact(cur, _best(), _history())
    assert v["paths"]["f32_packed_tb"]["verdict"] == "OK"
    assert v["paths"]["bf16_tb"]["verdict"] == "OK"
    assert v["paths"]["f32_packed_tb"]["reference"] == 15000.0
    # a >10% tb drop at the same window calibration regresses
    v = ps.check_artifact(dict(cur, tb_mcells=13000.0),
                          _best(), _history())
    assert v["paths"]["f32_packed_tb"]["verdict"] == "REGRESSION"
    assert any("f32_packed_tb" in m for m in v["regressions"])
    # a smaller measured grid than the reference's is amortization gap
    v = ps.check_artifact(dict(cur, tb_mcells=5000.0, tb_n=256),
                          _best(), _history())
    assert v["paths"]["f32_packed_tb"]["verdict"] == "INCONCLUSIVE"
    # a window where stage 3c never produced a number: NOT-MEASURED,
    # never a phantom regression
    v = ps.check_artifact(CUR_OK, _best(), _history())
    assert v["paths"]["f32_packed_tb"]["verdict"] == "NOT-MEASURED"
    assert v["status"] == "OK"


def test_sentinel_tb_ledger_diff():
    """Round-8 satellite: the ledger_tb fixture pair catches a blocked-
    kernel per-section bytes regression chip-free."""
    ps = _sentinel()
    with open(os.path.join(FIX, "ledger_tb_ref.json")) as f:
        ref = json.load(f)
    with open(os.path.join(FIX, "ledger_tb_regressed.json")) as f:
        cur = json.load(f)
    assert ps.check_ledgers(ref, ref)["status"] == "OK"
    v = ps.check_ledgers(cur, ref)
    assert v["status"] == "REGRESSION"
    assert any("packed-kernel-tb" in m for m in v["regressions"])
    # tb ledgers never diff against single-step packed ones (the whole
    # point is the per-step halving; a cross-kind diff would "regress")
    with open(os.path.join(FIX, "ledger_ref.json")) as f:
        pk_ref = json.load(f)
    assert ps.check_ledgers(ref, pk_ref)["status"] == "SKIPPED"
    # and the fixture pair encodes the roofline claim itself: the tb
    # reference's per-step bytes/cell sit at ~half the packed ref's
    ratio = ref["per_step"]["bytes_per_cell"] \
        / pk_ref["per_step"]["bytes_per_cell"]
    assert ratio <= 0.55, ratio


def test_sentinel_tb_depth_paths_registered():
    """Round-12 satellite: the per-depth k-sweep paths
    (f32_packed_tb_k3 / f32_packed_tb_k4, bench stage 3e) are first-
    class sentinel paths with their own grid keys — absent history
    reads NOT-MEASURED/NO-REF, never a phantom regression."""
    ps = _sentinel()
    cur = dict(CUR_OK, tb_k3_mcells=20000.0, tb_k3_n=640,
               tb_k4_mcells=24000.0, tb_k4_n=640)
    v = ps.check_artifact(cur, _best(), _history())
    # no reference on record yet (first k-sweep window): NO-REF
    assert v["paths"]["f32_packed_tb_k3"]["verdict"] == "NO-REF"
    assert v["paths"]["f32_packed_tb_k4"]["verdict"] == "NO-REF"
    assert v["status"] == "OK"
    # once a best carries the keys, drops gate like every other path
    best = dict(_best(), tb_k3_mcells=20000.0, tb_k3_n=640,
                tb_k4_mcells=24000.0, tb_k4_n=640)
    v = ps.check_artifact(dict(cur, tb_k3_mcells=15000.0), best,
                          _history())
    assert v["paths"]["f32_packed_tb_k3"]["verdict"] == "REGRESSION"
    assert v["paths"]["f32_packed_tb_k4"]["verdict"] == "OK"


def test_sentinel_tb_depth_ledger_fixture_pairs():
    """Round-12 satellite: a checked-in ledger fixture pair PER DEPTH
    — the byte-ratio regression is caught chip-free at k=3 and k=4,
    and cross-depth diffs are SKIPPED (a depth change legitimately
    moves per-step bytes; each depth gates against its own ref)."""
    ps = _sentinel()
    refs = {}
    for k in (3, 4):
        with open(os.path.join(FIX, f"ledger_tb_k{k}_ref.json")) as f:
            ref = json.load(f)
        with open(os.path.join(FIX,
                               f"ledger_tb_k{k}_regressed.json")) as f:
            cur = json.load(f)
        refs[k] = ref
        assert ref["steps_per_call"] == k
        assert ps.check_ledgers(ref, ref)["status"] == "OK"
        v = ps.check_ledgers(cur, ref)
        assert v["status"] == "REGRESSION", k
        assert any("packed-kernel-tb" in m for m in v["regressions"])
    # the fixture pairs encode the per-depth roofs themselves vs the
    # single-step packed reference (~16/12 B/cell/step classes)
    with open(os.path.join(FIX, "ledger_ref.json")) as f:
        pk_ref = json.load(f)
    for k, bound in ((3, 0.40), (4, 0.32)):
        ratio = refs[k]["per_step"]["bytes_per_cell"] \
            / pk_ref["per_step"]["bytes_per_cell"]
        assert ratio <= bound, (k, ratio)
    # cross-depth diff: SKIPPED, not a fake regression
    v = ps.check_ledgers(refs[4], refs[3])
    assert v["status"] == "SKIPPED" and "depth" in v["note"]


def test_sentinel_ledger_diff():
    ps = _sentinel()
    with open(os.path.join(FIX, "ledger_ref.json")) as f:
        ref = json.load(f)
    with open(os.path.join(FIX, "ledger_regressed.json")) as f:
        cur = json.load(f)
    v = ps.check_ledgers(ref, ref)
    assert v["status"] == "OK"
    v = ps.check_ledgers(cur, ref)
    assert v["status"] == "REGRESSION"
    assert any("cpml" in m for m in v["regressions"])
    # different step kinds never diff (apples to apples only)
    other = json.loads(json.dumps(ref))
    other["step_kind"] = "jnp"
    assert ps.check_ledgers(other, ref)["status"] == "SKIPPED"


def test_sentinel_cli_exit_codes(tmp_path):
    """Acceptance: non-zero exit on a synthetic >10% per-path
    regression against BENCH_BEST."""
    tool = os.path.join(ROOT, "tools", "perf_sentinel.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def run(cur, *extra):
        p = tmp_path / "cur.json"
        p.write_text(json.dumps(cur))
        return subprocess.run(
            [sys.executable, tool, str(p),
             "--best", os.path.join(FIX, "bench_best.json"),
             "--history", os.path.join(FIX, "bench_history_r*.json"),
             *extra],
            capture_output=True, text=True, timeout=120, env=env)

    bad = run(dict(CUR_OK, pallas_mcells=7000.0))
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stdout
    ok = run(CUR_OK)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # ledger lane through the CLI too
    led = run(CUR_OK, "--ledger",
              os.path.join(FIX, "ledger_regressed.json"),
              "--ledger-ref", os.path.join(FIX, "ledger_ref.json"))
    assert led.returncode == 1
    assert "cpml" in led.stderr


def test_bench_invokes_sentinel():
    """bench.py embeds the sentinel verdict in its artifact (the
    in-process hook; the full bench is a chip-window affair)."""
    import bench
    sentinel = bench._load_sentinel()
    out = dict(CUR_OK)
    verdict = sentinel.check_artifact(
        out, best=_best(), history=_history())
    assert verdict["status"] == "OK"
    # and the hook site exists in the measurement path
    import inspect
    src = inspect.getsource(bench.run_measurement)
    assert "perf_sentinel" in src and "check_artifact" in src


# -------------------------------------------------------------------------
# comm lane (round 10): sentinel gate, per-core attribution, aot_overlap
# -------------------------------------------------------------------------

def _comm_fix(name):
    with open(os.path.join(FIX, name)) as f:
        return json.load(f)


def test_sentinel_comm_lane_verdicts():
    """Acceptance: PASS on ref/ref, REGRESSION on regressed/ref —
    chip-free, from the checked-in v2-ledger fixture pair."""
    ps = _sentinel()
    ref = _comm_fix("comm_ref.json")
    bad = _comm_fix("comm_regressed.json")
    ok = ps.check_comm(ref, ref)
    assert ok["status"] == "OK" and not ok["regressions"]
    v = ps.check_comm(bad, ref)
    assert v["status"] == "REGRESSION"
    msgs = " | ".join(v["regressions"])
    assert "halo-bytes/chip" in msgs
    assert "overlap windows" in msgs
    assert "synchronous collective-permutes" in msgs
    # the fixture pair also encodes the overlap claim itself
    assert ref["comm"]["async_windows"]["windows_with_compute"] == 2
    assert bad["comm"]["async_windows"]["windows_with_compute"] == 0


def test_sentinel_comm_skips_cross_topology_and_v1():
    ps = _sentinel()
    ref = _comm_fix("comm_ref.json")
    other = json.loads(json.dumps(ref))
    other["comm"]["topology"] = [1, 2, 4]
    assert ps.check_comm(other, ref)["status"] == "SKIPPED"
    # a v1 ledger (no comm lane) skips rather than phantom-gating
    v1 = _comm_fix("ledger_ref.json")
    assert ps.check_comm(v1, ref)["status"] == "SKIPPED"
    # cross-kind never diffs
    jnp_led = json.loads(json.dumps(ref))
    jnp_led["step_kind"] = "jnp"
    assert ps.check_comm(jnp_led, ref)["status"] == "SKIPPED"


def test_sentinel_comm_missing_overlap_is_inconclusive():
    """A current ledger shipped WITHOUT an aot_overlap artifact while
    the reference gates overlap must say so (INCONCLUSIVE), never
    silently pass the window checks (review finding, round 10)."""
    ps = _sentinel()
    ref = _comm_fix("comm_ref.json")
    cur = json.loads(json.dumps(ref))
    del cur["comm"]["async_windows"]
    v = ps.check_comm(cur, ref)
    assert v["status"] == "INCONCLUSIVE"
    assert not v["regressions"]
    assert any("NOT evaluated" in m for m in v["inconclusive"])
    # the reverse (ref has no overlap on record) stays OK — there is
    # nothing to gate against
    v2 = ps.check_comm(ref, cur)
    assert v2["status"] == "OK"


def test_sentinel_comm_attribution_bar_gates():
    """A strategy change that loses the halo-exchange scoping (<95%
    attribution) is itself a regression — it blinds the lane."""
    ps = _sentinel()
    ref = _comm_fix("comm_ref.json")
    blind = json.loads(json.dumps(ref))
    blind["comm"]["per_step"]["halo_attribution"] = 0.80
    v = ps.check_comm(blind, ref)
    assert v["status"] == "REGRESSION"
    assert any("attribution" in m for m in v["regressions"])


def test_sentinel_comm_cli_exit_codes(tmp_path):
    tool = os.path.join(ROOT, "tools", "perf_sentinel.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps({"platform": "cpu"}))

    def run(comm_file):
        return subprocess.run(
            [sys.executable, tool, str(cur),
             "--best", os.path.join(FIX, "bench_best.json"),
             "--history", os.path.join(FIX, "bench_history_r*.json"),
             "--comm", os.path.join(FIX, comm_file),
             "--comm-ref", os.path.join(FIX, "comm_ref.json")],
            capture_output=True, text=True, timeout=120, env=env)

    ok = run("comm_ref.json")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = run("comm_regressed.json")
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "halo-bytes/chip" in bad.stderr


def test_sentinel_comm_tb_fixture_pair():
    """ISSUE-10 satellite: the temporal-blocked (2,2,2) ledger pair —
    a two-plane-exchange byte/message regression (or lost async
    lowering) on the SHARDED tb path is caught chip-free."""
    ps = _sentinel()
    ref = _comm_fix("comm_tb_ref.json")
    bad = _comm_fix("comm_tb_regressed.json")
    assert ref["step_kind"] == "pallas_packed_tb"
    assert ref["steps_per_call"] == 2
    # the ref encodes the depth-2 claims: traced == tb plan model,
    # full attribution, async strategy, compute inside every window
    assert ref["comm"]["per_step"]["ppermute_bytes_per_chip"] == \
        ref["comm"]["plan"]["halo_bytes_per_chip_per_step"]
    assert ref["comm"]["per_step"]["halo_attribution"] == 1.0
    assert ref["comm"]["strategy"]["ghost_depth"] == 2
    assert ref["comm"]["strategy"]["schedule"] == "async"
    aw = ref["comm"]["async_windows"]
    assert aw["sync_collective_permutes"] == 0
    assert aw["windows"] == aw["windows_with_compute"] == 4
    ok = ps.check_comm(ref, ref)
    assert ok["status"] == "OK" and not ok["regressions"]
    v = ps.check_comm(bad, ref)
    assert v["status"] == "REGRESSION"
    msgs = " | ".join(v["regressions"])
    assert "halo-bytes/chip" in msgs
    assert "messages" in msgs
    assert "attribution" in msgs
    assert "overlap windows" in msgs
    assert "synchronous collective-permutes" in msgs


def test_sentinel_tb_sharded_throughput_path():
    """The sharded-tb throughput path is first-class: its own keys, so
    a multichip-stage drop gates without polluting single-chip tb
    history."""
    ps = _sentinel()
    assert "f32_packed_tb_sharded" in ps.PATHS
    cur = {"platform": "tpu", "hbm_probe_gbps": 600.0,
           "tb_sharded_mcells": 800.0, "tb_sharded_n": 256}
    ref = {"hbm_probe_gbps": 600.0,
           "tb_sharded_mcells": 1000.0, "tb_sharded_n": 256}
    v = ps.check_artifact(cur, best=ref)
    row = v["paths"]["f32_packed_tb_sharded"]
    assert row["verdict"] == "REGRESSION"
    cur2 = dict(cur, tb_sharded_mcells=950.0)
    v2 = ps.check_artifact(cur2, best=ref)
    assert v2["paths"]["f32_packed_tb_sharded"]["verdict"] == "OK"


def test_aot_overlap_tb_hlo_fixture():
    """ISSUE-10 satellite: --hlo on the checked-in tb scheduled-HLO
    fixture proves the two-plane exchange lowers ASYNC with compute
    inside EVERY window — 4 start/done pairs (H(t), E(t+1), H(t+1),
    E(t+2)-fix generations), zero synchronous collective-permutes."""
    ao = _load_tool("aot_overlap")
    art = ao.overlap_artifact(
        ao.analyze(open(os.path.join(FIX,
                                     "overlap_tb_ref.hlo")).read()),
        "hlo:overlap_tb_ref.hlo")
    ao.validate_overlap(art)
    assert art["sync_collective_permutes"] == 0
    assert art["async_starts"] == art["async_dones"] == 4
    assert art["windows"] == 4
    assert art["windows_with_compute"] == 4   # every window


def test_aot_overlap_hlo_gate_chip_free(tmp_path):
    """tools/aot_overlap.py --hlo: the async-window analysis runs on a
    checked-in scheduled-HLO fixture with no TPU toolchain at all, and
    --out writes the schema-tagged artifact the comm lane embeds."""
    ao = _load_tool("aot_overlap")
    out = tmp_path / "overlap.json"
    rc = ao.main(["--hlo", os.path.join(FIX, "overlap_ref.hlo"),
                  "--out", str(out)])
    assert rc == 0
    art = json.loads(out.read_text())
    ao.validate_overlap(art)
    assert art["schema"] == "fdtd3d-overlap"
    assert art["sync_collective_permutes"] == 0
    assert art["async_starts"] == 2 and art["async_dones"] == 2
    assert art["windows"] == 2 and art["windows_with_compute"] == 2
    assert art["heavy_ops_inside_windows"] == 4
    with pytest.raises(ValueError, match="fdtd3d-overlap"):
        ao.validate_overlap({"schema": "nope"})


def test_trace_attribution_multicore_golden():
    """Satellite acceptance: the synthetic multi-core (TPU-shaped)
    fixture drives the per-core path — golden per-core sums, imbalance
    ratio, and the named top-straggler core."""
    ta = _load_tool("trace_attribution")
    path = os.path.join(FIX, "fixture.trace.multicore.json")
    events = ta._load_events(path)
    per_core = ta.attribute_events_per_core(events)
    assert set(per_core) == {"TPU:0", "TPU:1", "TPU:2", "TPU:3"}
    assert per_core["TPU:2"] == pytest.approx(
        {"packed-kernel": 0.330, "halo-exchange": 0.060,
         "health": 0.010})
    imb = ta.core_imbalance(per_core)
    assert imb["straggler"] == "TPU:2"
    assert imb["max_ms"] == pytest.approx(0.400)
    assert imb["mean_ms"] == pytest.approx(0.300)
    assert imb["ratio"] == pytest.approx(1.3333, abs=1e-4)
    # merged into the attribution record, still schema-valid
    graph_ms, host_ms = ta.attribute_events(events)
    rec = ta.merge_with_ledger(graph_ms, host_ms, None, path,
                               per_core=per_core)
    telemetry.validate_record(rec)
    assert rec["imbalance"]["straggler"] == "TPU:2"
    assert rec["per_core"]["TPU:3"]["total_ms"] == pytest.approx(0.300)
    txt = ta.format_text(rec)
    assert "straggler TPU:2" in txt
    # host-only/single-core events yield no per-core lane (no keys)
    rec2 = ta.merge_with_ledger(graph_ms, host_ms, None, path,
                                per_core={})
    assert "per_core" not in rec2 and "imbalance" not in rec2


def test_trace_attribution_core_name_variants():
    ta = _load_tool("trace_attribution")
    assert ta._core_of("/device:TPU:3") == "TPU:3"
    assert ta._core_of("TPU:1 (pid 7)") == "TPU:1"
    # chip AND core both survive: two chips' core-0 timelines must
    # not merge into one key (review finding, round 10)
    assert ta._core_of("Chip 0 Core 1") == "chip0-core1"
    assert ta._core_of("Chip 1 Core 1") == "chip1-core1"
    assert ta._core_of("Core 2") == "core:2"
    assert ta._core_of("python main thread") is None


def test_legacy_measure_tools_quarantined():
    """Satellite: measure_r3/r4 exit 2 without the explicit opt-in
    flag and still run (import-time) with it."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for tool in ("measure_r3.py", "measure_r4.py"):
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", tool)],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 2, (tool, proc.stdout, proc.stderr)
        assert "--i-know-this-is-legacy" in proc.stderr
    # the gate function itself accepts the flag (the full sweep is a
    # chip-window affair, not a tier-1 run)
    m3 = _load_tool("measure_r3")
    assert m3.require_legacy_flag(["--i-know-this-is-legacy"]) is True
    assert m3.require_legacy_flag([]) is False


def test_bench_embeds_multichip_summary():
    """Satellite: the bench artifact carries the MULTICHIP comm
    summary (modeled halo-bytes/chip + topology table; overlap windows
    and per-chip imbalance degrade to explanatory notes off-chip)."""
    import bench
    out = bench._comm_observability()
    assert out["topology"] == [2, 2, 2]
    assert out["halo_bytes_per_chip_per_step"] > 0
    assert "2.2.2" in out["halo_topology_table"]
    # chip-free container: both runtime lanes explain their absence
    assert out["overlap_windows"] is None or \
        "windows_with_compute" in out["overlap_windows"]
    # and the hook site exists in the measurement path
    import inspect
    src = inspect.getsource(bench.run_measurement)
    assert "_comm_observability" in src and '"multichip"' in src
    # with a telemetry file carrying v4 imbalance records, the worst
    # ratio + straggler surface
    out2 = bench._comm_observability(
        telemetry_path=os.path.join(FIX, "telemetry_v4.jsonl"))
    imb = out2["per_chip_imbalance"]
    assert imb["worst_ratio"] == pytest.approx(1.0333)
    assert imb["straggler_chip"] == 5 and imb["n_chips"] == 8


# -------------------------------------------------------------------------
# device-trace lane wiring
# -------------------------------------------------------------------------

def test_trace_capture_degrades_cleanly(tmp_path, monkeypatch):
    """No profiler -> warned no-op, never a crash or partial state."""
    import jax

    from fdtd3d_tpu import profiling

    def boom(*a, **k):
        raise RuntimeError("profiler unavailable on this backend")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    cap = profiling.TraceCapture(str(tmp_path / "trc"))
    assert cap.start() is False
    assert cap.start() is False  # idempotent, no retry storm
    cap.stop()                   # no-op, no crash
    with profiling.device_trace(str(tmp_path / "trc2")) as c:
        assert c.ok is False


def test_cli_profile_dir_writes_trace(tmp_path):
    """--profile DIR drives the capture through Simulation and the
    CLI finally finalizes it (mirrors the sink-close guarantee)."""
    from fdtd3d_tpu import cli
    from fdtd3d_tpu import log as _log
    d = str(tmp_path / "prof")
    lvl = _log.get_level()
    try:
        rc = cli.main(["--2d", "TMz", "--sizex", "16", "--sizey", "16",
                       "--sizez", "1", "--time-steps", "4",
                       "--point-source", "Ez", "--profile", d,
                       "--save-dir", str(tmp_path),
                       "--log-level", "0"])
    finally:
        _log.set_level(lvl)  # --log-level mutates the process-global
    assert rc == 0
    ta = _load_tool("trace_attribution")
    files = ta.find_trace_files(d)
    assert files, "no trace files written under --profile DIR"
    # and the parser accepts the real capture
    graph_ms, host_ms = ta.attribute_events(ta._load_events(files[0]))
    rec = ta.merge_with_ledger(graph_ms, host_ms, None, files[0])
    telemetry.validate_record(rec)


def test_bench_profile_env_plumbs_profile_dir(monkeypatch, tmp_path):
    """FDTD3D_BENCH_PROFILE routes a per-stage capture dir into the
    stage Simulation's OutputConfig (checked at config level: the full
    bench stage is a chip-window affair)."""
    import inspect

    import bench
    # _measure is the stage body (measure is the round-8 wrapper that
    # pins FDTD3D_NO_TEMPORAL for the legacy packed stages)
    src = inspect.getsource(bench._measure)
    assert "FDTD3D_BENCH_PROFILE" in src and "profile_dir" in src
    assert "sim.close()" in src


def test_config_for_kind_rejects_unknown():
    with pytest.raises(ValueError, match="unknown step kind"):
        costs.config_for_kind("warp-drive")


def test_cli_no_profile_compat_and_roundtrip(tmp_path):
    """--profile was a BooleanOptionalAction before round 7: command
    files saved by earlier builds contain --no-profile and must keep
    replaying; and save_cmd_file must not mis-serialize the hidden
    compat alias."""
    from fdtd3d_tpu import cli
    p = cli.build_parser()
    assert p.parse_args(["--no-profile"]).profile is False
    assert p.parse_args(["--profile"]).profile is True
    assert p.parse_args(["--profile", "/tmp/d"]).profile == "/tmp/d"
    # round-trip: True -> "--profile" only (no stray --no-profile line)
    args = p.parse_args(["--3d", "--profile"])
    out = tmp_path / "cmd.txt"
    cli.save_cmd_file(args, str(out))
    lines = out.read_text().splitlines()
    assert "--profile" in lines and \
        not any("--no-profile" in ln for ln in lines)
    # DIR form round-trips and replays to the same value
    args = p.parse_args(["--3d", "--profile", "/tmp/d"])
    cli.save_cmd_file(args, str(out))
    lines = out.read_text().splitlines()
    assert "--profile /tmp/d" in lines
    assert p.parse_args(cli.read_cmd_file(str(out))).profile == "/tmp/d"


# -------------------------------------------------------------------------
# compile-amortization lane (round 15, ISSUE 12): bench stage + sentinel
# -------------------------------------------------------------------------

_CA_OK = {"grid": 24, "steps": 8, "step_kind": "jnp",
          "exec_key": "a" * 64, "exec_key_comparable": "k" * 64,
          "cold_compile_ms": 1000.0, "warm_compile_ms": 0.0,
          "cold_traces": 1, "warm_traces": 0, "warm_hits": 1,
          "cache_enabled": True, "disk_dir": None}


def test_sentinel_compile_lane_verdicts():
    """check_compile: >25% cold growth at equal key regresses; a warm
    run that traces regresses outright; no equal-key reference or
    sub-floor compiles are INCONCLUSIVE, never a silent pass."""
    ps = _sentinel()
    ref = {"compile_amortization": dict(_CA_OK)}
    ok = ps.check_compile({"compile_amortization": dict(_CA_OK)},
                          best=ref)
    assert ok["status"] == "OK", ok
    # +20% is within the 25% threshold
    within = ps.check_compile(
        {"compile_amortization": dict(_CA_OK,
                                      cold_compile_ms=1200.0)},
        best=ref)
    assert within["status"] == "OK"
    reg = ps.check_compile(
        {"compile_amortization": dict(_CA_OK,
                                      cold_compile_ms=1400.0)},
        best=ref)
    assert reg["status"] == "REGRESSION"
    assert "equal exec key" in reg["regressions"][0]
    # a warm same-key run that traced = the cache broke
    warm = ps.check_compile(
        {"compile_amortization": dict(_CA_OK, warm_traces=1,
                                      warm_compile_ms=950.0)},
        best=ref)
    assert warm["status"] == "REGRESSION"
    assert "not amortizing" in warm["regressions"][0]
    # with the off-switch set, a traced warm run is expected — no gate
    off = ps.check_compile(
        {"compile_amortization": dict(_CA_OK, warm_traces=1,
                                      cache_enabled=False)},
        best=ref)
    assert not any("amortizing" in r for r in off["regressions"])
    # a DIFFERENT comparable key (kernel/tile/grid changed): the cold
    # number is not comparable — inconclusive, not regression
    other = ps.check_compile(
        {"compile_amortization": dict(_CA_OK, cold_compile_ms=9000.0,
                                      exec_key_comparable="z" * 64)},
        best=ref)
    assert other["status"] == "INCONCLUSIVE"
    # sub-noise-floor compiles wobble with load: inconclusive
    floor_ref = {"compile_amortization": dict(_CA_OK,
                                              cold_compile_ms=50.0)}
    floor = ps.check_compile(
        {"compile_amortization": dict(_CA_OK, cold_compile_ms=90.0)},
        best=floor_ref)
    assert floor["status"] == "INCONCLUSIVE"
    # no stage at all: skipped with a note
    assert ps.check_compile({}, best=ref)["status"] == "SKIPPED"


def test_bench_compile_amortization_stage():
    """The bench stage itself, CPU-deterministic: cold run traces
    once, warm run traces zero and hits the cache; the artifact
    carries both ExecKey digests, and run_measurement embeds the
    stage + the sentinel's compile lane."""
    import inspect

    import bench
    ca = bench.compile_amortization(n=12, steps=4)
    assert ca["cold_traces"] == 1 and ca["warm_traces"] == 0
    assert ca["warm_hits"] == 1
    assert ca["cold_compile_ms"] > 0.0
    assert ca["warm_compile_ms"] == 0.0
    assert len(ca["exec_key"]) == 64
    assert len(ca["exec_key_comparable"]) == 64
    assert ca["exec_key"] != ca["exec_key_comparable"]
    src = inspect.getsource(bench.run_measurement)
    assert "compile_amortization" in src and "check_compile" in src
    # and the live stage passes its own sentinel gate vs itself
    ps = _sentinel()
    verdict = ps.check_compile({"compile_amortization": ca},
                               best={"compile_amortization": ca})
    assert verdict["status"] in ("OK", "INCONCLUSIVE")


def test_sentinel_cli_compile_lane(tmp_path):
    """A warm-traced compile stage fails the standalone sentinel CLI
    (exit 1) even when every throughput path is OK."""
    tool = os.path.join(ROOT, "tools", "perf_sentinel.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cur = dict(CUR_OK,
               compile_amortization=dict(_CA_OK, warm_traces=1))
    p = tmp_path / "cur.json"
    p.write_text(json.dumps(cur))
    proc = subprocess.run(
        [sys.executable, tool, str(p),
         "--best", os.path.join(FIX, "bench_best.json"),
         "--history", os.path.join(FIX, "bench_history_r*.json")],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "not amortizing" in proc.stderr


# -------------------------------------------------------------------------
# round 16: lane-capable batched packed paths + batched ledger fixtures
# -------------------------------------------------------------------------

def test_sentinel_batch_paths_registered():
    """Round-16 satellite: the batched-packed paths (f32_packed_batch /
    bf16_batch, bench stage 4b) are first-class sentinel paths with
    their own grid keys — absent history reads NOT-MEASURED/NO-REF,
    never a phantom regression; once a best carries the keys, drops
    gate like every other path."""
    ps = _sentinel()
    cur = dict(CUR_OK, batch_mcells=7500.0, batch_n=256,
               batch_bf16_mcells=13000.0, batch_bf16_n=256)
    v = ps.check_artifact(cur, _best(), _history())
    assert v["paths"]["f32_packed_batch"]["verdict"] == "NO-REF"
    assert v["paths"]["bf16_batch"]["verdict"] == "NO-REF"
    assert v["status"] == "OK"
    best = dict(_best(), batch_mcells=7500.0, batch_n=256,
                batch_bf16_mcells=13000.0, batch_bf16_n=256)
    v = ps.check_artifact(dict(cur, batch_mcells=5000.0), best,
                          _history())
    assert v["paths"]["f32_packed_batch"]["verdict"] == "REGRESSION"
    assert v["paths"]["bf16_batch"]["verdict"] == "OK"
    # smaller-grid window than the reference's: INCONCLUSIVE, not a cry
    v = ps.check_artifact(dict(cur, batch_mcells=5000.0, batch_n=192),
                          best, _history())
    assert v["paths"]["f32_packed_batch"]["verdict"] == "INCONCLUSIVE"


def test_sentinel_batch_ledger_fixture_pair():
    """Round-16 satellite: the ledger_batch fixture pair catches a
    per-lane field-traffic regression chip-free, and batched ledgers
    never diff across batch widths (nor against solo ledgers) — the
    per-lane normalization makes magnitudes comparable, but the
    lane-amortized comm shares and the VMEM-surcharged tile pick move
    with the width."""
    ps = _sentinel()
    with open(os.path.join(FIX, "ledger_batch_ref.json")) as f:
        ref = json.load(f)
    with open(os.path.join(FIX, "ledger_batch_regressed.json")) as f:
        cur = json.load(f)
    assert ref["batch"] == 3
    assert ps.check_ledgers(ref, ref)["status"] == "OK"
    v = ps.check_ledgers(cur, ref)
    assert v["status"] == "REGRESSION"
    assert any("E-update" in m or "per-step" in m
               for m in v["regressions"])
    # batch-width mismatch (incl. vs a solo ledger): SKIPPED
    with open(os.path.join(FIX, "ledger_ref.json")) as f:
        solo = json.load(f)
    assert ps.check_ledgers(ref, solo)["status"] == "SKIPPED"
    assert ps.check_ledgers(dict(ref, batch=2), ref)["status"] \
        == "SKIPPED"
