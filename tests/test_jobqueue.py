"""Unit coverage for the durable job queue (fdtd3d_tpu/jobqueue.py):
journal fold semantics, quota admission, priority aging, coalesce
grouping, placement scoring, the sched_crash fault grammar/hook, the
queue metrics, and the registry-relative artifact resolution the
fleet tools share (registry.resolve_artifact)."""

import json
import os
import subprocess
import sys

import pytest

from fdtd3d_tpu import faults, jobqueue, registry, telemetry
from fdtd3d_tpu.jobqueue import JobQueue, QuotaError, QuotaPolicy

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures")


@pytest.fixture(autouse=True)
def _no_fault_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _spec(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


BASE = ("--3d\n--same-size 12\n--time-steps 8\n--courant-factor 0.4\n"
        "--wavelength 0.008\n")


# -------------------------------------------------------------------------
# fault grammar: sched_crash@job=N + misspelled-scope rejection
# -------------------------------------------------------------------------

def test_sched_crash_grammar_parses_and_rejects_misscopes():
    plan = faults.FaultPlan.parse("sched_crash@job=2")
    f = plan.faults[0]
    assert f.kind == "sched_crash" and f.job == 2
    # a key the kind would silently ignore is rejected, not ignored
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("sched_crash@n=2")
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("sched_crash@t=2")
    # job= does not apply to the other kinds either
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("preempt@job=1")
    with pytest.raises(ValueError, match="must be an integer"):
        faults.FaultPlan.parse("sched_crash@job=x")


def test_on_sched_journal_fires_once_at_its_ordinal():
    faults.install("sched_crash@job=2")
    faults.on_sched_journal(1)          # not this dispatch
    with pytest.raises(faults.SimulatedPreemption,
                       match="scheduler crashed"):
        faults.on_sched_journal(2)
    faults.on_sched_journal(2)          # one-shot: spent
    faults.clear()
    faults.on_sched_journal(2)          # no plan: no-op


def test_fallback_group_still_offers_its_dispatch_ordinal(
        tmp_path, monkeypatch):
    """A coalesced group whose BatchSimulation constructor rejects it
    consumed dispatch ordinal N: sched_crash@job=N must still be able
    to fire there (a silently skipped ordinal would shift every later
    fault target off the documented 'a group is ONE dispatch'
    grammar)."""
    import fdtd3d_tpu.batch as _batch
    q = JobQueue(str(tmp_path / "q"))
    a = q.submit(_spec(tmp_path, "a.txt", BASE), tenant="acme")
    b = q.submit(_spec(tmp_path, "b.txt", BASE + "--eps 2.0\n"),
                 tenant="acme")

    def _reject(*args, **kwargs):
        raise ValueError("forced constructor rejection")

    monkeypatch.setattr(_batch, "BatchSimulation", _reject)
    faults.install("sched_crash@job=1")
    with pytest.raises(faults.SimulatedPreemption,
                       match="dispatch #1"):
        jobqueue.Scheduler(q).serve()
    # the crash landed before any running row: replay re-dispatches
    # both jobs (solo, the constructor still rejects the group)
    faults.clear()
    out = jobqueue.Scheduler(q).serve()
    assert out["jobs"][a]["status"] == "completed"
    assert out["jobs"][b]["status"] == "completed"


def test_requeue_resets_wait_clock(tmp_path, monkeypatch):
    """wait_s measures QUEUE time: a requeued job's next dispatch
    reports the wait since its `queued` transition, not since submit
    (its first run's 10 minutes must not fire the queue-wait SLO)."""
    q = JobQueue(str(tmp_path / "q"))
    now = {"t": 1000.0}
    monkeypatch.setattr(jobqueue.time, "time", lambda: now["t"])
    jid = q.submit(_spec(tmp_path, "a.txt", BASE), tenant="acme")
    sched = jobqueue.Scheduler(q)
    assert sched._wait_s(q.jobs()[jid]) == 0.0
    now["t"] = 1600.0   # the job ran 10 minutes, then was preempted
    sched._state(q.jobs()[jid], "queued", reason="requeued")
    job = q.jobs()[jid]
    assert job["unix"] == 1600.0    # the fold overlays the reset
    now["t"] = 1605.0
    assert sched._wait_s(job) == 5.0


# -------------------------------------------------------------------------
# admission + journal fold
# -------------------------------------------------------------------------

def test_submit_quota_rejection_names_tenant_and_bound(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    spec = _spec(tmp_path, "a.txt", BASE)
    pol = QuotaPolicy(max_queued=1)
    q.submit(spec, tenant="acme", policy=pol)
    with pytest.raises(QuotaError, match="'acme'.*max_queued.*1"):
        q.submit(spec, tenant="acme", policy=pol)
    # another tenant's backlog is not acme's problem
    q.submit(spec, tenant="globex", policy=pol)


def test_submit_rejects_unloadable_specs(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    with pytest.raises(ValueError, match="no such file"):
        q.submit(str(tmp_path / "nope.txt"))
    bad = _spec(tmp_path, "bad.txt", "--no-such-flag 1\n")
    with pytest.raises(ValueError, match="does not parse"):
        q.submit(bad)
    nested = _spec(tmp_path, "nested.txt", BASE + "--batch x.txt\n")
    with pytest.raises(ValueError, match="--batch"):
        q.submit(nested)


def test_journal_fold_age_and_reason_scoping(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    spec = _spec(tmp_path, "a.txt", BASE)
    j1 = q.submit(spec, tenant="a")
    j2 = q.submit(spec, tenant="b")
    q.cancel(j1)                      # terminal transition
    j3 = q.submit(spec, tenant="c")
    jobs = q.jobs()
    assert jobs[j1]["status"] == "cancelled"
    # age = terminal transitions journaled after the submit row
    assert jobs[j2]["age"] == 1 and jobs[j3]["age"] == 0
    # a terminal job cannot be cancelled again (named)
    with pytest.raises(ValueError, match="already terminal"):
        q.cancel(j1)
    with pytest.raises(ValueError, match="no such job"):
        q.cancel("j-99999-zzzz")
    # every journal row validates under the telemetry schema
    for rec in q.read():
        telemetry.validate_record(json.loads(json.dumps(rec)))


def test_fold_reason_rides_one_transition(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    spec = _spec(tmp_path, "a.txt", BASE)
    jid = q.submit(spec, tenant="a")
    q._emit("job_state", job_id=jid, tenant="a", status="queued",
            reason="requeued after restart")
    q._emit("job_state", job_id=jid, tenant="a", status="completed",
            t=8)
    row = q.jobs()[jid]
    assert row["status"] == "completed"
    assert "reason" not in row      # the requeue reason did not leak


def test_effective_priority_aging_lifts_starved_jobs(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    sched = jobqueue.Scheduler(q, policy=QuotaPolicy(aging=1.0))
    old_low = {"priority": 0, "age": 3}
    new_high = {"priority": 2, "age": 0}
    assert sched._effective_priority(old_low) > \
        sched._effective_priority(new_high)


# -------------------------------------------------------------------------
# coalescing
# -------------------------------------------------------------------------

def test_coalesce_key_groups_same_shape_only(tmp_path):
    a = jobqueue.load_spec(_spec(tmp_path, "a.txt",
                                 BASE + "--eps 1.0\n"))
    b = jobqueue.load_spec(_spec(tmp_path, "b.txt",
                                 BASE + "--eps 4.0\n"))
    other = jobqueue.load_spec(_spec(tmp_path, "c.txt",
                                     BASE.replace("8", "24")))
    assert jobqueue.coalesce_key(a) == jobqueue.coalesce_key(b)
    assert jobqueue.coalesce_key(a) != jobqueue.coalesce_key(other)
    ds = jobqueue.load_spec(_spec(
        tmp_path, "d.txt", BASE + "--dtype float32x2\n"))
    assert jobqueue.coalesce_key(ds) is None   # runs solo, documented


def test_coalesce_unit_respects_tenant_cell_quota(tmp_path):
    q = JobQueue(str(tmp_path / "q"))
    spec = _spec(tmp_path, "a.txt", BASE)      # 12^3 = 1728 cells
    j1 = q.submit(spec, tenant="acme")
    j2 = q.submit(spec, tenant="acme")
    j3 = q.submit(spec, tenant="globex")
    sched = jobqueue.Scheduler(
        q, policy=QuotaPolicy(max_concurrent_cells=2000.0))
    jobs = q.jobs()
    queued = sorted(jobs.values(), key=lambda j: j["submit_idx"])
    used = {j1}
    cfg = sched._load(jobs[j1]["spec"])
    unit = sched._coalesce_unit(jobs[j1], cfg, queued, used)
    ids = {j["job_id"] for j in unit}
    # acme's second job would blow its 2000-cell cap; globex's fits
    assert ids == {j1, j3}
    # without the cap all three share the executable
    sched2 = jobqueue.Scheduler(q)
    unit2 = sched2._coalesce_unit(jobs[j1], cfg, queued, {j1})
    assert {j["job_id"] for j in unit2} == {j1, j2, j3}


# -------------------------------------------------------------------------
# placement scoring
# -------------------------------------------------------------------------

def test_score_topology_picks_min_halo_and_honors_exclusions(
        tmp_path):
    cfg = jobqueue.load_spec(_spec(
        tmp_path, "a.txt",
        "--3d\n--same-size 16\n--time-steps 8\n--courant-factor 0.4\n"
        "--wavelength 0.008\n--topology auto\n"))
    topo, rec = jobqueue.score_topology(cfg, 8)
    from fdtd3d_tpu import costs
    table = costs.halo_topology_table(cfg, 8)
    assert topo[0] * topo[1] * topo[2] == 8
    # the choice achieves the table's minimum modeled halo bytes
    # (several factorizations tie; the async-schedule tie-break picks)
    assert rec["halo_bytes_per_chip_step"] == min(table.values())
    assert table[".".join(map(str, topo))] == min(table.values())
    assert rec["excluded_chips"] == []
    # excluding stragglers shrinks the pool: 8 - 6 = 2 usable chips
    topo2, rec2 = jobqueue.score_topology(
        cfg, 8, exclude_chips=(0, 1, 2, 3, 4, 5))
    assert topo2[0] * topo2[1] * topo2[2] == 2
    assert rec2["excluded_chips"] == [0, 1, 2, 3, 4, 5]
    # a pool of one chip is unsharded, no record
    assert jobqueue.score_topology(cfg, 1) == ((1, 1, 1), None)


def test_place_honors_explicit_topology_requests(tmp_path):
    sched = jobqueue.Scheduler(JobQueue(str(tmp_path / "q")))
    none_cfg = jobqueue.load_spec(_spec(tmp_path, "n.txt", BASE))
    out, rec, pool = sched.place(none_cfg)
    assert out is none_cfg and rec is None and pool is None
    manual = jobqueue.load_spec(_spec(
        tmp_path, "m.txt",
        BASE + "--topology manual\n--manual-topology 2x1x1\n"))
    out, rec, pool = sched.place(manual)
    # pinned, not rescored, and the tenant's device set untouched
    assert out is manual and rec is None and pool is None
    auto = jobqueue.load_spec(_spec(
        tmp_path, "a.txt", BASE + "--topology auto\n"))
    out, rec, pool = sched.place(auto)
    assert out.parallel.topology in ("manual", "none")
    assert rec is None or rec["halo_bytes_per_chip_step"] > 0
    assert pool is not None and len(pool) >= 1


def _convicting_registry(tmp_path, chips, n=4):
    """A forged registry whose telemetry stream convicts ``chips``
    (each crowned imbalance-argmax in ``n`` chunks)."""
    reg = tmp_path / "runs.jsonl"
    reg.write_text(json.dumps(
        {"v": 8, "type": "run_begin", "run_id": "r1",
         "status": "running", "kind": "cli", "wall_time": "w",
         "git_sha": "s", "platform": "cpu",
         "telemetry_path": "t.jsonl"}) + "\n")
    rows = []
    chunk = 0
    for chip in chips:
        for _ in range(n):
            chunk += 1
            rows.append({"v": 8, "type": "imbalance", "chunk": chunk,
                         "t": 4 * chunk, "metric": "energy",
                         "max": 3.0, "mean": 1.0, "ratio": 3.0,
                         "argmax": chip, "n_chips": 8})
    (tmp_path / "t.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows))
    return str(reg)


def test_place_pool_really_excludes_convicted_chips(tmp_path):
    """The exclusion is physical, not just arithmetical: the device
    pool handed to the dispatch (and so to the mesh build) contains
    no convicted chip, and the scored topology fits inside it."""
    reg = _convicting_registry(tmp_path, chips=(0, 1))
    sched = jobqueue.Scheduler(JobQueue(str(tmp_path / "q")),
                               registry_path=reg)
    auto = jobqueue.load_spec(_spec(
        tmp_path, "a.txt",
        "--3d\n--same-size 16\n--time-steps 8\n--courant-factor 0.4\n"
        "--wavelength 0.008\n--topology auto\n"))
    out, rec, pool = sched.place(auto)
    assert rec["excluded_chips"] == [0, 1]
    assert all(d.id not in (0, 1) for d in pool)
    topo = out.parallel.manual_topology or (1, 1, 1)
    assert topo[0] * topo[1] * topo[2] <= len(pool)
    # and the registry is read ONCE per scheduler, not per dispatch
    assert sched.place(auto)[2] is pool


def test_dispatch_threads_excluded_pool_into_sim(tmp_path,
                                                 monkeypatch):
    """A dispatched auto job's mesh is built from the filtered pool:
    the convicted chip hosts no shard (the `devices=` plumbing the
    journal's excluded_chips row claims)."""
    import fdtd3d_tpu.supervisor as _sup
    reg = _convicting_registry(tmp_path, chips=(0,))
    q = JobQueue(str(tmp_path / "q"))
    jid = q.submit(_spec(
        tmp_path, "a.txt",
        "--3d\n--same-size 16\n--time-steps 4\n--courant-factor 0.4\n"
        "--wavelength 0.008\n--topology auto\n"), tenant="acme")
    seen = {}
    real = _sup.Supervisor

    def spy(*args, **kwargs):
        seen["devices"] = kwargs.get("devices")
        return real(*args, **kwargs)

    monkeypatch.setattr(_sup, "Supervisor", spy)
    out = jobqueue.Scheduler(q, registry_path=reg).serve()
    assert out["jobs"][jid]["status"] == "completed"
    assert seen["devices"] is not None
    assert all(d.id != 0 for d in seen["devices"])


def test_coalesced_auto_group_survives_degenerate_pool(tmp_path,
                                                       monkeypatch):
    """Two coalescible --topology auto jobs on a pool that degenerates
    to one chip still share ONE BatchSimulation: every lane is
    re-pinned to the placed (possibly unsharded) decomposition, so
    the fingerprints cannot split on parallel.topology."""
    import jax
    one = jax.devices()[:1]
    monkeypatch.setattr(jax, "devices", lambda *a: one)
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY",
                       str(tmp_path / "runs.jsonl"))
    q = JobQueue(str(tmp_path / "q"))
    spec = ("--3d\n--same-size 12\n--time-steps 4\n"
            "--courant-factor 0.4\n--wavelength 0.008\n"
            "--topology auto\n")
    a = q.submit(_spec(tmp_path, "a.txt", spec), tenant="acme")
    b = q.submit(_spec(tmp_path, "b.txt", spec + "--eps 2.0\n"),
                 tenant="acme")
    out = jobqueue.Scheduler(q).serve()
    jobs = out["jobs"]
    assert jobs[a]["status"] == jobs[b]["status"] == "completed"
    # shared one group (solo fallback would leave group unset)
    assert jobs[a].get("group") and \
        jobs[a]["group"] == jobs[b].get("group")
    assert jobs[a]["run_id"] == jobs[b]["run_id"]


def test_straggler_chips_reads_the_registry_rollup(tmp_path):
    reg = tmp_path / "runs.jsonl"
    tele = tmp_path / "t.jsonl"
    rows = [
        {"v": 8, "type": "run_begin", "run_id": "r1",
         "status": "running", "kind": "cli", "wall_time": "w",
         "git_sha": "s", "platform": "cpu",
         "telemetry_path": "t.jsonl"},
    ]
    reg.write_text("".join(json.dumps(r) + "\n" for r in rows))
    recs = []
    for chunk in range(1, 5):
        recs.append({"v": 8, "type": "imbalance", "chunk": chunk,
                     "t": 4 * chunk, "metric": "energy", "max": 3.0,
                     "mean": 1.0, "ratio": 3.0, "argmax": 5,
                     "n_chips": 8})
    tele.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert jobqueue.straggler_chips(str(reg), threshold=3) == [5]
    assert jobqueue.straggler_chips(str(reg), threshold=5) == []
    assert jobqueue.straggler_chips(None) == []
    assert jobqueue.straggler_chips(str(tmp_path / "nope")) == []


# -------------------------------------------------------------------------
# queue metrics (the journal feeds the exposition)
# -------------------------------------------------------------------------

def test_queue_metrics_from_fixture_journal():
    from fdtd3d_tpu.metrics import MetricsRegistry
    reg = MetricsRegistry.from_jsonl(os.path.join(FIX,
                                                  "queue_v8.jsonl"))
    assert reg.value("jobs_submitted_total", tenant="acme") == 2
    assert reg.value("jobs_submitted_total", tenant="globex") == 1
    assert reg.value("jobs_total", status="completed",
                     tenant="acme") == 1
    assert reg.value("jobs_total", status="failed",
                     tenant="acme") == 1
    assert reg.value("jobs_total", status="completed",
                     tenant="globex") == 1
    assert reg.value("queue_depth") == 0     # fixture ends drained
    text = reg.render()
    assert "fdtd3d_queue_wait_seconds_count" in text
    assert 'fdtd3d_jobs_total{status="failed",tenant="acme"} 1' \
        in text
    assert text.strip().endswith("# EOF")


# -------------------------------------------------------------------------
# registry-relative artifact resolution (the shared resolver)
# -------------------------------------------------------------------------

def _begin_row(rid, tele):
    return {"v": 8, "type": "run_begin", "run_id": rid,
            "status": "running", "kind": "queue", "wall_time": "w",
            "git_sha": "s", "platform": "cpu",
            "telemetry_path": tele}


def _stream(path):
    recs = [
        {"v": 8, "type": "run_start", "wall_time": "w",
         "git_sha": "s", "jax_version": "j", "platform": "cpu",
         "device_kind": "cpu", "hbm_gbps": None},
        {"v": 8, "type": "chunk", "chunk": 1, "t": 4, "steps": 4,
         "wall_s": 0.01, "mcells_per_s": 4.0, "energy": 1.0,
         "div_l2": 0.1, "div_linf": 0.2, "max_e": 0.1, "max_h": 0.1,
         "finite": True, "vmem_rung": 0},
        {"v": 8, "type": "run_end", "t": 4, "steps": 4,
         "wall_s": 0.01, "mcells_per_s": 4.0,
         "first_unhealthy_t": None},
    ]
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_resolve_artifact_uses_registry_dir_not_cwd(tmp_path,
                                                    monkeypatch):
    """Satellite regression: rows written from two different working
    directories carry relative telemetry paths; both must resolve
    against the REGISTRY's directory from any reader CWD."""
    regdir = tmp_path / "fleet"
    regdir.mkdir()
    reg = regdir / "runs.jsonl"
    _stream(str(regdir / "a.jsonl"))
    _stream(str(regdir / "b.jsonl"))
    cwd_a = tmp_path / "writer_a"
    cwd_b = tmp_path / "writer_b"
    cwd_a.mkdir()
    cwd_b.mkdir()
    monkeypatch.chdir(cwd_a)
    registry.RunRegistry(str(reg)).emit(
        "run_begin", **_begin_row("r-a", "a.jsonl"))
    monkeypatch.chdir(cwd_b)
    registry.RunRegistry(str(reg)).emit(
        "run_begin", **_begin_row("r-b", "b.jsonl"))
    reader_cwd = tmp_path / "reader"
    reader_cwd.mkdir()
    monkeypatch.chdir(reader_cwd)
    # the resolver itself
    assert registry.resolve_artifact(str(reg), "a.jsonl") == \
        str(regdir / "a.jsonl")
    assert registry.resolve_artifact(str(reg), "missing.jsonl") \
        is None
    assert registry.resolve_artifact(str(reg), None) is None
    # fleet_report joins BOTH streams from a foreign CWD
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import importlib
    fleet_report = importlib.import_module("fleet_report")
    rollup = fleet_report.build_rollup(str(reg))
    assert rollup["runs"]["r-a"]["telemetry"] == "a.jsonl"
    assert rollup["runs"]["r-b"]["telemetry"] == "b.jsonl"
    # slo_gate --registry (no positional stream) judges both,
    # run-id-joined, from the same foreign CWD
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "slo_gate.py"),
         "--registry", str(reg)],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(reader_cwd))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "a.jsonl" in proc.stdout and "b.jsonl" in proc.stdout


# -------------------------------------------------------------------------
# group snapshot resume: preempted coalesced groups continue from the
# committed t, bit-identical (round 16)
# -------------------------------------------------------------------------

def test_group_preemption_resumes_from_committed_snapshot(tmp_path):
    """A preempted coalesced group does NOT restart from t=0: the
    re-dispatch adopts the group's committed snapshot (one .npz per
    chunk boundary under <queue>/groups/<gid>/), journals the resume t
    on its "running" rows, and finishes every lane bit-identical to an
    uninterrupted run of the same pair."""
    import numpy as np
    from fdtd3d_tpu import exec_cache, io

    def _serve_pair(tag, fault=None):
        q = JobQueue(str(tmp_path / tag))
        a = q.submit(_spec(tmp_path, f"{tag}_a.txt",
                           BASE + "--eps 1.0\n"), tenant="acme")
        b = q.submit(_spec(tmp_path, f"{tag}_b.txt",
                           BASE + "--eps 2.0\n"), tenant="acme")
        if fault:
            faults.install(fault)
        try:
            out = jobqueue.Scheduler(q, batch_chunk=4).serve()
        finally:
            faults.clear()
        jobs = out["jobs"]
        assert jobs[a]["status"] == "completed"
        assert jobs[b]["status"] == "completed"
        assert jobs[a]["group"] == jobs[b]["group"]
        gdir = os.path.join(q.dirpath, "groups", jobs[a]["group"])
        final = os.path.join(gdir, "ckpt_t000008.npz")
        assert os.path.exists(final), sorted(os.listdir(gdir))
        return q, (a, b), final

    # preempt@t=8 fires on the second chunk boundary, BEFORE that
    # boundary's snapshot commits: the only committed snapshot is t=4
    exec_cache.clear_memory()
    traces0 = exec_cache.stats()["traces"]
    q, (a, b), final = _serve_pair("faulted", fault="preempt@t=8")

    rows = [r for r in q.read() if r.get("type") == "job_state"]
    pre = [r for r in rows if r.get("status") == "preempted"]
    assert len(pre) == 2 and {r["job_id"] for r in pre} == {a, b}
    for r in pre:
        assert "committed snapshot t=4" in r["reason"]
        assert r["t"] == 8          # preempted at t=8, resumes from 4
    runs_a = [r for r in rows
              if r.get("status") == "running" and r["job_id"] == a]
    assert [r.get("resumed_from") for r in runs_a] == [0, 4]
    runs_b = [r for r in rows
              if r.get("status") == "running" and r["job_id"] == b]
    assert [r.get("resumed_from") for r in runs_b] == [0, 4]

    # the re-dispatch re-used the cached vmap chunk executable: one
    # trace covers both dispatches (same ExecKey, same batch width)
    assert exec_cache.stats()["traces"] - traces0 == 1

    # bit-identical: the resumed group's final snapshot matches an
    # uninterrupted run of the same pair, array for array
    _, _, ref_final = _serve_pair("clean")
    s_res, m_res = io.load_checkpoint(final)
    s_ref, m_ref = io.load_checkpoint(ref_final)
    assert m_res["t"] == m_ref["t"] == 8

    def _leaves(tree, prefix=""):
        for k in sorted(tree):
            v = tree[k]
            if isinstance(v, dict):
                yield from _leaves(v, f"{prefix}{k}/")
            else:
                yield f"{prefix}{k}", v

    res_leaves = dict(_leaves(s_res))
    ref_leaves = dict(_leaves(s_ref))
    assert set(res_leaves) == set(ref_leaves) and res_leaves
    for key, arr in ref_leaves.items():
        assert np.array_equal(arr, res_leaves[key]), key
