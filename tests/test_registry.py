"""Run registry (fdtd3d_tpu/registry.py): the append-only fleet index.

Load-bearing claims (ISSUE 13 tentpole piece 1):

* with ``FDTD3D_RUN_REGISTRY`` set, a run appends exactly one
  ``run_begin`` (status running) and one ``run_final`` row, both
  schema-v7-valid, via single atomic O_APPEND writes;
* the ``run_id`` is stamped into the telemetry ``run_start`` AND the
  checkpoint metadata, so streams and snapshots join the index;
* the ``exec_key_comparable`` digest is stable across runs of the
  same scenario (the fleet's scenario-identity join key);
* status derivation: completed / failed (exception or unrecovered
  non-finite) / recovered (recovery events or isolated lanes);
* supervisor sim-swaps never double-register (suppress + transfer);
* the knob unset is a true no-op.
"""

import json
import os

import numpy as np
import pytest

from fdtd3d_tpu import io, registry, telemetry
from fdtd3d_tpu.config import (OutputConfig, PmlConfig,
                               PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation


def _cfg(tmp_path, **out_kw):
    out_kw.setdefault("telemetry_path", str(tmp_path / "t.jsonl"))
    return SimConfig(
        scheme="3D", size=(12, 12, 12), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(6, 6, 6)),
        output=OutputConfig(save_dir=str(tmp_path / "out"), **out_kw))


def test_no_registry_without_knob(tmp_path, monkeypatch):
    monkeypatch.delenv("FDTD3D_RUN_REGISTRY", raising=False)
    sim = Simulation(_cfg(tmp_path))
    try:
        assert sim.run_registry is None and sim.run_id is None
        sim.advance(8)
    finally:
        sim.close()
    recs = telemetry.read_jsonl(str(tmp_path / "t.jsonl"))
    assert "run_id" not in recs[0]


def test_registry_rows_and_joins(tmp_path, monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    sim = Simulation(_cfg(tmp_path))
    try:
        rid = sim.run_id
        assert isinstance(rid, str) and rid
        # begin row already on disk, status running
        rows = registry.read(reg)
        assert [r["type"] for r in rows] == ["run_begin"]
        assert rows[0]["status"] == "running"
        assert rows[0]["run_id"] == rid
        assert rows[0]["grid"] == [12, 12, 12]
        assert rows[0]["telemetry_path"] == str(tmp_path / "t.jsonl")
        digest = rows[0]["exec_key_comparable"]
        assert isinstance(digest, str) and len(digest) == 64
        sim.advance(4)
        sim.advance(4)
    finally:
        sim.close()
    rows = registry.read(reg)  # validates every row (schema v7)
    assert [r["type"] for r in rows] == ["run_begin", "run_final"]
    final = rows[1]
    assert final["status"] == "completed"
    assert final["run_id"] == rid
    assert final["steps"] == 8 and final["t"] == 8
    assert final["recovery_events"]["total"] == 0
    assert final["first_unhealthy_t"] is None
    assert isinstance(final["compile_ms"], (int, float))
    # joins: telemetry run_start + checkpoint meta carry the run_id
    recs = telemetry.read_jsonl(str(tmp_path / "t.jsonl"))
    assert recs[0]["run_id"] == rid
    assert sim.extra_ckpt_meta["run_id"] == rid
    # close() is idempotent: no duplicate final row
    sim.close()
    assert len(registry.read(reg)) == 2
    # a second run of the SAME scenario shares the comparable digest
    # (the scenario-identity join key) under a fresh run_id
    sim2 = Simulation(_cfg(tmp_path))
    try:
        assert sim2.run_id != rid
        sim2.advance(8)
    finally:
        sim2.close()
    folded = registry.fold(registry.read(reg))
    assert len(folded) == 2
    assert folded[sim2.run_id]["exec_key_comparable"] == digest
    assert all(r["status"] == "completed" for r in folded.values())


def test_registry_failed_on_health_trip(tmp_path, monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    sim = Simulation(_cfg(tmp_path, check_finite=True))
    try:
        bad = np.full((12, 12, 12), np.nan, np.float32)
        sim.set_field("Ez", bad)
        with pytest.raises(FloatingPointError):
            sim.advance(4)
    finally:
        sim.close()   # inside the test frame: no live exception here
    # the sink recorded the unhealthy chunk -> unrecovered non-finite
    # completion reads as failed
    final = registry.read(reg)[-1]
    assert final["type"] == "run_final"
    assert final["status"] == "failed"
    assert final["first_unhealthy_t"] == 4


def test_registry_failed_when_exception_propagates(tmp_path,
                                                   monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    sim = Simulation(_cfg(tmp_path, telemetry_path=None))
    try:
        raise RuntimeError("simulated driver crash")
    except RuntimeError:
        sim.close()   # the CLI-finally shape: close amid propagation
    final = registry.read(reg)[-1]
    assert final["status"] == "failed"


def test_registry_recovered_from_recovery_events(tmp_path,
                                                 monkeypatch):
    """A run whose sink recorded recovery events folds to
    'recovered' (the supervisor path emits these through the same
    sink; the derivation is what's under test here — the full
    supervised chain runs in tests/test_fleet_e2e.py)."""
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    sim = Simulation(_cfg(tmp_path))
    try:
        sim.advance(8)
        sim.telemetry.emit("rollback", t_failed=8, t_restored=0,
                           source="initial-snapshot",
                           reason="test", chip=None, host=None)
    finally:
        sim.close()
    final = registry.read(reg)[-1]
    assert final["status"] == "recovered"
    assert final["recovery_events"]["rollback"] == 1


def test_registry_without_telemetry_sink(tmp_path, monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    sim = Simulation(_cfg(tmp_path, telemetry_path=None))
    try:
        sim.advance(8)
    finally:
        sim.close()
    final = registry.read(reg)[-1]
    assert final["status"] == "completed"
    assert final["t"] == 8


def test_suppress_and_transfer(tmp_path, monkeypatch):
    reg = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg)
    with registry.suppress_registration():
        sim = Simulation(_cfg(tmp_path, telemetry_path=None))
    assert sim.run_registry is None
    assert not os.path.exists(reg)
    # transfer moves the handle + stamps (the supervisor swap shape)
    sim_a = Simulation(_cfg(tmp_path, telemetry_path=None))
    handle = sim_a.run_registry
    assert handle is not None
    with registry.suppress_registration():
        sim_b = Simulation(_cfg(tmp_path, telemetry_path=None))
    registry.transfer(sim_a, sim_b)
    assert sim_a.run_registry is None
    assert sim_b.run_registry is handle
    assert sim_b.run_id == handle.run_id
    assert sim_b.extra_ckpt_meta["run_id"] == handle.run_id
    sim_b.close()
    rows = registry.read(reg)
    assert [r["type"] for r in rows] == ["run_begin", "run_final"]
    sim_a.close()  # no handle anymore: must not write a second final
    assert len(registry.read(reg)) == 2


def test_atomic_append_whole_lines(tmp_path):
    path = str(tmp_path / "idx.jsonl")
    io.atomic_append(path, json.dumps({"a": 1}) + "\n")
    io.atomic_append(path, json.dumps({"b": 2}) + "\n")
    lines = open(path).read().splitlines()
    assert [json.loads(ln) for ln in lines] == [{"a": 1}, {"b": 2}]


def test_fold_last_status_wins():
    rows = [
        {"v": 7, "type": "run_begin", "run_id": "x",
         "status": "running", "kind": "cli", "wall_time": "w",
         "git_sha": "s", "platform": "cpu", "grid": [4, 4, 4]},
        {"v": 7, "type": "run_final", "run_id": "x",
         "status": "recovered", "t": 8, "steps": 8, "wall_s": 0.1,
         "mcells_per_s": 1.0},
    ]
    folded = registry.fold(rows)
    assert folded["x"]["status"] == "recovered"
    assert folded["x"]["grid"] == [4, 4, 4]   # begin fields survive
    assert folded["x"]["kind"] == "cli"
