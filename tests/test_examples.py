"""Acceptance suite: black-box replay of every Examples/*.txt config.

The reference's acceptance tests are black-box runs of the full binary on
small .txt configs with correctness asserted on printed error norms
(SURVEY.md §4). Here: every example command file is replayed through the
real CLI entry (cmd-file parsing included); 3D BASELINE-scale configs are
shrunk by override flags (CLI flags override the file, reference
behavior); the final printed field norms must match golden values
recorded from a validated run. A norm drift beyond ~0.5% means the
physics changed.

The two BASELINE multi-chip configs (sphere3D_mie, drude3D_nanoantenna)
use --topology auto, so on the 8-device test mesh this suite also
exercises the sharded path end-to-end from the CLI.
"""

import contextlib
import glob
import io
import os
import re

import pytest

from fdtd3d_tpu import cli

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "Examples")

_SHRINK_3D = ["--same-size", "32", "--time-steps", "60", "--pml-size", "4",
              "--tfsf-margin", "3", "--norms-every", "60"]

# file -> (override argv, golden final norms). Goldens recorded on the
# 8-device CPU mesh, f32; tolerance covers platform/fusion reorderings.
CASES = {
    "vacuum1D_ezhy.txt": ([], {"Ez": 9.9848e-01, "Hy": 2.6546e-03}),
    "drude1D_metal.txt": ([], {"Ez": 1.0683e+00, "Hy": 5.3137e-03}),
    "vacuum2D_tmz.txt": ([], {"Ez": 6.0252e-02, "Hx": 6.5954e-05,
                              "Hy": 6.5954e-05}),
    "metamaterial1D_dng.txt": ([], {"Ez": 2.2762e-01, "Hy": 6.0649e-04}),
    "vacuum3D_tfsf.txt": (
        ["--same-size", "32", "--time-steps", "60", "--pml-size", "5",
         "--tfsf-margin", "4", "--norms-every", "60"],
        {"Ex": 3.2531e-01, "Hy": 8.3379e-04}),
    "sphere3D_mie.txt": (
        _SHRINK_3D + ["--eps-sphere-center-x", "16",
                      "--eps-sphere-center-y", "16",
                      "--eps-sphere-center-z", "16",
                      "--eps-sphere-radius", "6"],
        {"Ex": 4.4693e-02, "Ey": 6.1280e-03, "Ez": 7.6921e-03,
         "Hy": 1.2000e-04}),
    "precision3D_compensated.txt": (
        ["--same-size", "32", "--time-steps", "60", "--pml-size", "4",
         "--point-source-x", "16", "--point-source-y", "16",
         "--point-source-z", "16", "--norms-every", "60"],
        {"Ex": 6.4461e-02, "Ez": 1.5448e-01, "Hy": 5.0197e-05}),
    "drude3D_nanoantenna.txt": (
        _SHRINK_3D + ["--drude-sphere-center-x", "16",
                      "--drude-sphere-center-y", "16",
                      "--drude-sphere-center-z", "16",
                      "--drude-sphere-radius", "6"],
        {"Ex": 4.4692e-02, "Ey": 9.9613e-03, "Ez": 1.3982e-02,
         "Hy": 1.2808e-04}),
    # --use-pallas on: replays the packed-ds kernel (interpret mode
    # here) — the CPU jnp-ds fallback's cold XLA compile of the EFT
    # graph is minutes-slow (tests/test_float32x2.py docstring), while
    # the kernel path compiles in seconds and is the path the example
    # documents
    "precision3D_float32x2.txt": (
        ["--use-pallas", "on", "--same-size", "24", "--time-steps",
         "40", "--pml-size", "4", "--tfsf-margin", "3",
         "--norms-every", "40"],
        {"Ex": 3.0504e-02, "Ey": 4.7151e-02, "Ez": 3.1139e-02,
         "Hy": 1.0143e-04}),
}

RTOL = 5e-3


def _run_cli(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def test_every_example_has_a_case():
    files = {os.path.basename(p)
             for p in glob.glob(os.path.join(EXAMPLES_DIR, "*.txt"))}
    assert files == set(CASES), (
        "every Examples/*.txt must be replayed by this suite")


@pytest.mark.parametrize("name", sorted(CASES))
def test_example_replay_golden_norms(name):
    overrides, golden = CASES[name]
    rc, out = _run_cli(
        ["--cmd-from-file", os.path.join(EXAMPLES_DIR, name)] + overrides)
    assert rc == 0, f"{name}: CLI exited {rc}\n{out}"
    norm_lines = [ln for ln in out.splitlines() if ln.startswith("[t=")]
    assert norm_lines, f"{name}: no norms printed\n{out}"
    norms = dict(re.findall(r"(\w+)=([\d.e+-]+)", norm_lines[-1]))
    for comp, want in golden.items():
        got = float(norms[comp])
        assert got == pytest.approx(want, rel=RTOL), (
            f"{name}: {comp} = {got:.6e}, golden {want:.6e}")


@pytest.mark.parametrize("name", sorted(CASES))
def test_example_parses_and_validates_at_full_scale(name):
    """The unshrunk config (BASELINE scale) must parse and validate."""
    argv = cli.read_cmd_file(os.path.join(EXAMPLES_DIR, name))
    args = cli.build_parser().parse_args(argv)
    cfg = cli.args_to_config(args)
    cfg.validate()
    assert cfg.time_steps > 0


def test_save_cmd_to_file_roundtrip(tmp_path):
    """--save-cmd-to-file re-emits flags that reproduce the same config
    when replayed with --cmd-from-file (reference Settings parity)."""
    out = str(tmp_path / "cmd.txt")
    argv = ["--3d", "--same-size", "48", "--time-steps", "123",
            "--courant-factor", "0.4", "--wavelength", "15e-3",
            "--use-pml", "--pml-size", "6",
            "--use-tfsf", "--tfsf-margin", "4", "--angle-teta", "30",
            "--use-drude", "--eps-inf", "2.0", "--omega-p", "1e11",
            "--drude-sphere-radius", "5"]
    parser = cli.build_parser()
    args = parser.parse_args(argv)
    cli.save_cmd_file(args, out)
    cfg_direct = cli.args_to_config(parser.parse_args(argv))
    replay = cli.read_cmd_file(out)
    cfg_replayed = cli.args_to_config(parser.parse_args(replay))
    assert cfg_direct == cfg_replayed


@pytest.mark.parametrize("argv,want_kind", [
    # 3D + pallas forced (interpret mode on CPU) -> the sourceless hot
    # path since round 8 is the temporal-blocked packed kernel
    (["--3d", "--same-size", "16", "--time-steps", "2", "--use-pml",
      "--pml-size", "2", "--use-pallas", "on"], "pallas_packed_tb"),
    # pallas off -> jnp, stated explicitly at startup
    (["--3d", "--same-size", "16", "--time-steps", "2",
      "--use-pallas", "off"], "jnp"),
    # auto on the CPU test backend -> jnp (interpret mode is test-only)
    (["--3d", "--same-size", "16", "--time-steps", "2"], "jnp"),
])
def test_cli_prints_engaged_step_kind(argv, want_kind):
    """Startup observability (VERDICT r2 item 7): the engaged kernel path
    is printed and matches the expectation per config."""
    rc, out = _run_cli(argv)
    assert rc == 0, out
    kind_lines = [ln for ln in out.splitlines()
                  if ln.startswith("step_kind=")]
    assert kind_lines, f"no step_kind line printed\n{out}"
    assert kind_lines[0].split()[0] == f"step_kind={want_kind}", \
        kind_lines[0]
    if want_kind.startswith("pallas"):
        assert "tile=" in kind_lines[0] and "vmem_block=" in kind_lines[0]


def test_require_pallas_errors_on_fallback():
    """--require-pallas turns the silent jnp fallback into a hard error
    (here: 2D mode is pallas-ineligible)."""
    with pytest.raises((ValueError, SystemExit)):
        _run_cli(["--2d", "TMz", "--same-size", "16", "--time-steps", "2",
                  "--use-pallas", "on", "--require-pallas"])


def test_save_cmd_survives_default_drift(tmp_path):
    """A saved command file pins the FULL effective settings: replaying
    it under changed parser defaults must reproduce the original config
    (VERDICT r2 weak item 7 — silent meaning drift)."""
    out = str(tmp_path / "cmd.txt")
    argv = ["--3d", "--same-size", "32", "--use-pml"]  # pml-size default
    parser = cli.build_parser()
    args = parser.parse_args(argv)
    cli.save_cmd_file(args, out)
    cfg_direct = cli.args_to_config(parser.parse_args(argv))
    # simulate a future release changing defaults — including a BOOLEAN
    # default flipping to True (ADVICE r3: False must be representable
    # in the saved file, via the --no- forms BooleanOptionalAction adds)
    drifted = cli.build_parser()
    drifted.set_defaults(pml_size=4, courant_factor=0.9,
                         time_steps=7, dtype="bfloat16",
                         use_tfsf=True, compensated=True)
    cfg_replayed = cli.args_to_config(
        drifted.parse_args(cli.read_cmd_file(out)))
    assert cfg_direct == cfg_replayed


@pytest.mark.parametrize("name", ["sphere3D_mie.txt",
                                  "drude3D_nanoantenna.txt"])
def test_baseline_multichip_configs_engage_packed(name):
    """VERDICT r4 item 1 done-criterion, round-17 tightened: the
    BASELINE multi-chip validation workloads (#4 Mie sphere, #5 Drude
    nanoantenna — both SOURCED: TFSF, #5 also Drude + material grids)
    must run the flagship kernel under --topology auto on a mesh —
    since the widened sharded boundary wedge that is the TEMPORAL-
    BLOCKED kernel (~24 B/cell/step), no longer the 48 B/cell
    single-step packed kernel, and never the 72 B/cell two-pass
    fallback. Overrides come from CASES so this stays in lockstep
    with the acceptance replay's shrunk geometry."""
    from fdtd3d_tpu import cli as _cli
    argv = _cli.read_cmd_file(os.path.join(EXAMPLES_DIR, name)) \
        + CASES[name][0] + ["--use-pallas", "on"]
    args = _cli.build_parser().parse_args(argv)
    cfg = _cli.args_to_config(args)
    from fdtd3d_tpu.sim import Simulation
    sim = Simulation(cfg)
    assert sim.mesh is not None, "auto topology did not engage the mesh"
    assert sim.step_kind == "pallas_packed_tb", sim.step_kind
    assert "tb_fallback" not in (sim.step_diag or {})
    sim.advance(2)
    import numpy as np
    for c, v in sim.fields().items():
        assert np.isfinite(v).all(), c
