"""Machine-precision cavity-eigenmode oracles for ALL 13 scheme modes.

Every scheme mode — each 1D pair, each 2D TE/TM polarization, and full 3D
— initializes an exact discrete eigenmode (exact.cavity_mode) and must
track the analytic discrete-dispersion time evolution to ~1e-10 in f64.
This replaces the 'runs and stays finite' smoke level for the non-3D
modes with the same oracle strength the reference's polynomial callbacks
give every mode (SURVEY.md §4).
"""

import numpy as np
import pytest

from fdtd3d_tpu import diag, exact
from fdtd3d_tpu.config import SimConfig
from fdtd3d_tpu.layout import SCHEME_MODES, component_axis
from fdtd3d_tpu.sim import Simulation

SIZES = (17, 21, 13)   # per-axis extents when active
MODES_N = (2, 3, 1)    # per-axis mode numbers when active
STEPS = 100


def _setup(name):
    mode = SCHEME_MODES[name]
    size = tuple(SIZES[a] if a in mode.active_axes else 1 for a in range(3))
    mnp = tuple(MODES_N[a] if a in mode.active_axes else 0 for a in range(3))
    e_axes = sorted(component_axis(c) for c in mode.e_components)
    if len(e_axes) == 1:
        avec = tuple(1.0 if a == e_axes[0] else 0.0 for a in range(3))
        kw = {"avec": avec}
    elif len(e_axes) == 2:
        # TE_a: A = K x e_a lies in the E-plane and is divergence-free.
        missing = ({0, 1, 2} - set(e_axes)).pop()
        k = [mnp[a] * np.pi / (size[a] - 1) if size[a] > 1 else 0.0
             for a in range(3)]
        bigk = np.array([2.0 * np.sin(ka / 2.0) for ka in k])
        e_m = np.eye(3)[missing]
        kw = {"avec": tuple(np.cross(bigk, e_m))}
    else:
        kw = {}
    return mode, size, mnp, kw


@pytest.mark.parametrize("name", sorted(SCHEME_MODES))
def test_cavity_mode_exact_evolution(name):
    mode, size, mnp, kw = _setup(name)
    cfg = SimConfig(scheme=name, size=size, time_steps=STEPS, dx=1e-3,
                    courant_factor=0.5, wavelength=10e-3, dtype="float64")
    sim = Simulation(cfg)
    shapes, omega = exact.cavity_mode(size, mnp, cfg.dx, cfg.dt, **kw)
    assert set(shapes) == set(mode.e_components), (
        f"{name}: oracle produced {set(shapes)}, scheme has "
        f"{set(mode.e_components)}")
    for comp, shape in shapes.items():
        sim.set_field(comp, shape)
    sim.run()
    for comp, shape in shapes.items():
        expected = exact.cavity_expectation(shape, omega, cfg.dt, STEPS)
        norms = diag.error_norms(sim.field(comp), expected)
        scale = np.max(np.abs(expected))
        assert norms["linf"] < 1e-10 * max(scale, 1.0), \
            f"{name}/{comp}: {norms['linf']:.2e} (rel_l2 {norms['rel_l2']:.2e})"
    # H fields must actually be in motion (the mode is not static)
    assert max(np.abs(sim.field(c)).max() for c in mode.h_components) > 0.0
