"""AOT executable cache (fdtd3d_tpu/exec_cache.py) — ISSUE 12.

The compile-amortization acceptance surface, CPU-deterministic:

* a second same-key Simulation performs ZERO traces (counter-asserted)
  and reproduces the first's fields bit-for-bit;
* the ExecKey separates every graph-shaping axis — comm strategy,
  temporal-block depth, health/per-chip lanes, physics config — since
  a collision would silently reuse the wrong physics;
* the on-disk layer survives a PROCESS boundary (subprocess test),
  and a stale-provenance or truncated entry reads as a NAMED miss
  (warned), never a traceback.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fdtd3d_tpu import exec_cache, telemetry
from fdtd3d_tpu.config import (OutputConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation


def _cfg(n=12, **kw):
    kw.setdefault("pml", PmlConfig(size=(3, 3, 3)))
    return SimConfig(
        scheme="3D", size=(n, n, n), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(n // 2,) * 3), **kw)


def test_second_sim_zero_traces_and_bit_identical():
    """THE tentpole acceptance: a repeat scenario skips compile — the
    second Simulation with an identical ExecKey never calls lower()."""
    cfg = _cfg()
    sim1 = Simulation(cfg)
    sim1.advance(8)
    mid = exec_cache.stats()
    sim2 = Simulation(cfg)
    sim2.advance(8)
    end = exec_cache.stats()
    assert end["traces"] == mid["traces"], \
        "second same-key Simulation traced"
    assert end["compiles"] == mid["compiles"]
    assert end["hits"] == mid["hits"] + 1
    a = np.asarray(sim1.state["E"]["Ez"])
    b = np.asarray(sim2.state["E"]["Ez"])
    assert a.max() > 0 and np.array_equal(a, b)
    # the warm sim's own compile wall is ~0 (nothing compiled)
    assert sim2._compile_ms == 0.0
    assert sim1._compile_ms > 0.0


def test_counters_surface_in_telemetry(tmp_path):
    """run_start carries the at-construction aot_cache snapshot and
    run_end the final counters + the run's compile_ms — so warm vs
    cold is auditable from the JSONL alone."""
    cfg = _cfg()
    path = tmp_path / "t.jsonl"

    def with_sink(c):
        return dataclasses.replace(
            c, output=OutputConfig(telemetry_path=str(path)))

    sim1 = Simulation(with_sink(cfg))
    sim1.advance(8)
    sim1.close()
    sim2 = Simulation(with_sink(cfg))
    sim2.advance(8)
    sim2.close()
    recs = telemetry.read_jsonl(str(path))
    starts = [r for r in recs if r["type"] == "run_start"]
    ends = [r for r in recs if r["type"] == "run_end"]
    assert len(starts) == 2 and len(ends) == 2
    for r in starts + ends:
        assert isinstance(r["aot_cache"], dict)
    # the second run saw at least one more hit than the first did at
    # ITS start, and compiled nothing itself
    assert ends[1]["aot_cache"]["hits"] > starts[1]["aot_cache"]["hits"] \
        or starts[1]["aot_cache"]["hits"] > starts[0]["aot_cache"]["hits"]
    assert ends[1]["compile_ms"] == 0.0
    assert ends[0]["compile_ms"] > 0.0


def test_key_distinct_per_health_and_per_chip_lane():
    cfg = _cfg()
    base = dict(step_kind="jnp", topology=(1, 1, 1), n_steps=8)
    k0 = exec_cache.make_key(cfg, health=False, **base)
    k1 = exec_cache.make_key(cfg, health=True, **base)
    k2 = exec_cache.make_key(cfg, health=True, per_chip=True, **base)
    assert len({k0.digest, k1.digest, k2.digest}) == 3


def test_key_distinct_per_comm_strategy(monkeypatch):
    """Two configs differing ONLY in the comm-strategy override must
    key separately — the compiled exchange posture differs, and a
    collision would reuse the wrong executable."""
    cfg = _cfg(n=16, parallel=ParallelConfig(
        topology="manual", manual_topology=(2, 2, 2)))
    base = dict(step_kind="jnp", topology=(2, 2, 2), n_steps=8)
    monkeypatch.delenv("FDTD3D_COMM_STRATEGY", raising=False)
    k0 = exec_cache.make_key(cfg, **base)
    monkeypatch.setenv("FDTD3D_COMM_STRATEGY", "per-plane,sync")
    k1 = exec_cache.make_key(cfg, **base)
    assert k0.digest != k1.digest
    assert "per-plane" in (k1.comm_strategy or "")


def test_key_distinct_per_tb_depth(monkeypatch):
    """FDTD3D_TB_DEPTH=2 vs 3 (same everything else) must yield
    distinct keys for the temporal-blocked kind: the pipeline depth
    changes the compiled kernel."""
    cfg = _cfg(n=32)
    base = dict(step_kind="pallas_packed_tb", topology=(1, 1, 1),
                n_steps=8)
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "2")
    k2 = exec_cache.make_key(cfg, **base)
    monkeypatch.setenv("FDTD3D_TB_DEPTH", "3")
    k3 = exec_cache.make_key(cfg, **base)
    assert k2.ghost_depth == 2 and k3.ghost_depth == 3
    assert k2.digest != k3.digest
    # the provenance-free comparable digest separates them too (the
    # perf sentinel's "equal key" must never conflate depths)
    assert k2.comparable_digest != k3.comparable_digest


def test_key_distinct_per_physics_and_avals():
    base = dict(step_kind="jnp", topology=(1, 1, 1), n_steps=8)
    k0 = exec_cache.make_key(_cfg(), **base)
    # different PML thickness = different slab graph
    k1 = exec_cache.make_key(_cfg(pml=PmlConfig(size=(4, 4, 4))),
                             **base)
    assert k0.digest != k1.digest
    # avals axis: same cfg, different argument shapes
    k2 = exec_cache.make_key(_cfg(), avals_fp="deadbeef", **base)
    assert k2.digest != k0.digest


def test_cache_off_switch(monkeypatch):
    """FDTD3D_AOT_CACHE=0: every compile traces, nothing is shared —
    the pre-cache behavior, still counted."""
    monkeypatch.setenv("FDTD3D_AOT_CACHE", "0")
    cfg = _cfg(n=10)
    s0 = exec_cache.stats()
    Simulation(cfg).advance(8)
    Simulation(cfg).advance(8)
    s1 = exec_cache.stats()
    assert s1["traces"] == s0["traces"] + 2
    assert s1["hits"] == s0["hits"]
    assert not s1["enabled"]


def test_disk_layer_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("FDTD3D_AOT_CACHE_DIR", str(tmp_path))
    # the in-process layer may already hold this key from an earlier
    # test — publishing happens on COMPILE, so start cold
    exec_cache.clear_memory()
    cfg = _cfg()
    sim1 = Simulation(cfg)
    sim1.advance(8)
    entries = sorted(os.listdir(tmp_path))
    assert any(e.endswith(".aotx") for e in entries)
    assert any(e.endswith(".json") for e in entries)
    # drop the in-process layer: the reload must come from disk
    exec_cache.clear_memory()
    s0 = exec_cache.stats()
    sim2 = Simulation(cfg)
    sim2.advance(8)
    s1 = exec_cache.stats()
    assert s1["disk_hits"] == s0["disk_hits"] + 1
    assert s1["traces"] == s0["traces"]
    assert np.array_equal(np.asarray(sim1.state["E"]["Ez"]),
                          np.asarray(sim2.state["E"]["Ez"]))


def test_disk_truncated_entry_is_named_miss(tmp_path, monkeypatch,
                                            capsys):
    monkeypatch.setenv("FDTD3D_AOT_CACHE_DIR", str(tmp_path))
    exec_cache.clear_memory()
    cfg = _cfg()
    Simulation(cfg).advance(8)
    aotx = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    assert aotx
    path = os.path.join(str(tmp_path), aotx[0])
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    exec_cache.clear_memory()
    s0 = exec_cache.stats()
    sim = Simulation(cfg)
    sim.advance(8)   # must recompile cleanly, not crash
    s1 = exec_cache.stats()
    assert s1["disk_load_failures"] == s0["disk_load_failures"] + 1
    assert s1["traces"] == s0["traces"] + 1
    err = capsys.readouterr().err
    assert "aot cache" in err and "miss" in err
    assert float(np.abs(np.asarray(sim.state["E"]["Ez"])).max()) > 0


def test_disk_stale_provenance_is_miss(tmp_path, monkeypatch, capsys):
    """A forged/copied entry whose meta names another build must not
    load — even under the current digest's file name."""
    monkeypatch.setenv("FDTD3D_AOT_CACHE_DIR", str(tmp_path))
    exec_cache.clear_memory()
    cfg = _cfg()
    Simulation(cfg).advance(8)
    metas = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    assert metas
    mpath = os.path.join(str(tmp_path), metas[0])
    with open(mpath) as f:
        meta = json.load(f)
    meta["git_sha"] = "0000000000ff"
    with open(mpath, "w") as f:
        json.dump(meta, f)
    exec_cache.clear_memory()
    s0 = exec_cache.stats()
    Simulation(cfg).advance(8)
    s1 = exec_cache.stats()
    assert s1["disk_load_failures"] == s0["disk_load_failures"] + 1
    assert s1["traces"] == s0["traces"] + 1
    assert "stale entry" in capsys.readouterr().err


_CHILD = r"""
import json, os
import numpy as np
from fdtd3d_tpu.config import SimConfig, PmlConfig, PointSourceConfig
from fdtd3d_tpu.sim import Simulation
from fdtd3d_tpu import exec_cache
cfg = SimConfig(scheme="3D", size=(12, 12, 12), time_steps=8, dx=1e-3,
                courant_factor=0.4, wavelength=8e-3,
                pml=PmlConfig(size=(3, 3, 3)),
                point_source=PointSourceConfig(enabled=True,
                                               component="Ez",
                                               position=(6, 6, 6)))
sim = Simulation(cfg)
sim.advance(8)
s = exec_cache.stats()
ez = np.asarray(sim.state["E"]["Ez"], dtype=np.float64)
print(json.dumps({"traces": s["traces"], "disk_hits": s["disk_hits"],
                  "sum": float(ez.sum()), "max": float(ez.max())}))
"""


def test_disk_cache_survives_process_boundary(tmp_path):
    """ISSUE 12 acceptance: the on-disk layer works ACROSS processes —
    the second process compiles nothing (0 traces, 1 disk hit) and
    produces the identical field."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "FDTD3D_AOT_CACHE_DIR": str(tmp_path),
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    env.pop("FDTD3D_AOT_CACHE", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              cwd=root, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    cold, warm = outs
    assert cold["traces"] == 1 and cold["disk_hits"] == 0
    assert warm["traces"] == 0 and warm["disk_hits"] == 1, warm
    assert warm["sum"] == cold["sum"] and warm["max"] == cold["max"]


def test_scenario_spec_separable():
    """The three-object split: one ScenarioSpec can drive several
    Simulations (memoized host work), and its fingerprint matches the
    exec-cache key's config axis."""
    from fdtd3d_tpu.scenario import ScenarioSpec
    spec = ScenarioSpec(_cfg())
    sim1 = Simulation(spec)
    sim2 = Simulation(spec)
    assert sim1.spec is spec and sim2.spec is spec
    assert spec.fingerprint() == \
        exec_cache.config_fingerprint(spec.cfg)
    assert sim1.exec_key(8).digest == sim2.exec_key(8).digest


@pytest.mark.parametrize("n_steps", [4])
def test_sharded_sims_share_executable(n_steps):
    """Same-key SHARDED sims reuse the executable too (the mesh is
    rebuilt per sim, but the compiled artifact is keyed, not the
    mesh object)."""
    cfg = _cfg(n=16, parallel=ParallelConfig(
        topology="manual", manual_topology=(2, 2, 2)))
    sim1 = Simulation(cfg)
    sim1.advance(n_steps)
    mid = exec_cache.stats()
    sim2 = Simulation(cfg)
    sim2.advance(n_steps)
    end = exec_cache.stats()
    assert end["traces"] == mid["traces"]
    assert end["hits"] == mid["hits"] + 1
    assert np.array_equal(np.asarray(sim1.field("Ez")),
                          np.asarray(sim2.field("Ez")))


def test_aot_compile_sharded_shared_build():
    """The shared AOT build layer (tools/aot_overlap.py's former
    private path): compiles the production runner over an explicit
    mesh through the cache (second call = memory hit), and a
    require_kinds mismatch raises BEFORE any lowering."""
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs.reshape(2, 2), ("y", "z"))
    cfg = _cfg(n=16)
    with pytest.raises(exec_cache.WrongStepKind, match="jnp"):
        exec_cache.aot_compile_sharded(
            cfg, (1, 2, 2), mesh, 8, "cpu-test",
            require_kinds=("pallas_packed",))
    runner, compiled, info = exec_cache.aot_compile_sharded(
        cfg, (1, 2, 2), mesh, 8, "cpu-test")
    assert runner.kind == "jnp" and compiled is not None
    _r2, c2, info2 = exec_cache.aot_compile_sharded(
        cfg, (1, 2, 2), mesh, 8, "cpu-test")
    assert info2["source"] == "memory" and c2 is compiled
    # the overlap tool routes through this exact function
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "aot_overlap", os.path.join(root, "tools", "aot_overlap.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import inspect
    assert "aot_compile_sharded" in inspect.getsource(
        mod.build_compiled)


def test_key_distinct_per_device_subset():
    """Review finding (round 15): compiled executables are DEVICE-
    pinned — two sims on the same topology but different device
    subsets must key (and compile) separately, and each runs on its
    own devices."""
    import jax
    cfg = _cfg(n=16, parallel=ParallelConfig(
        topology="manual", manual_topology=(2, 1, 1)))
    devs = jax.devices()
    sim_a = Simulation(cfg, devices=devs[:2])
    sim_b = Simulation(cfg, devices=devs[2:4])
    ka = sim_a.exec_key(4)
    kb = sim_b.exec_key(4)
    assert ka.devices == (devs[0].id, devs[1].id)
    assert kb.devices == (devs[2].id, devs[3].id)
    assert ka.digest != kb.digest
    s0 = exec_cache.stats()
    sim_a.advance(4)
    sim_b.advance(4)
    s1 = exec_cache.stats()
    assert s1["traces"] == s0["traces"] + 2   # no cross-subset reuse
    assert np.array_equal(np.asarray(sim_a.field("Ez")),
                          np.asarray(sim_b.field("Ez")))
    used_b = {sh.device.id for sh in
              sim_b.state["E"]["Ez"].addressable_shards}
    assert used_b == {devs[2].id, devs[3].id}
