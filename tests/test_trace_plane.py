"""Causal trace plane (ISSUE 17 tentpole + satellites).

The trace plane's contract is a JOIN: one ``trace_id`` minted at
``JobQueue.submit`` must connect the queue journal, the run registry,
the telemetry stream, and the checkpoint meta — across preemptions and
scheduler crashes — well enough that ``tools/trace_export.py`` can
render the job's whole life as ONE Perfetto timeline and
``tools/fleet_report.py`` can decompose its wall time into phases.

* unit: the ``phase_budget`` SLO rule (span p95 vs per-phase budget,
  SKIPPED on pre-v9 streams), the slo_gate exit-code contract on a
  clean vs inflated-queue-wait stream, the metrics trace-join
  (``runs_total`` counts logical jobs, not dispatches) and the four
  span-fed phase histograms, and the v9 fixture's version gate;
* e2e (chip-free, 8 host devices): two tenants coalesce into one
  group on a (2, 2, 2) mesh, lane 1 is hit by an injected NaN, the
  group is preempted mid-run, and a ``sched_crash`` kills the
  scheduler after the re-dispatch completes.  A restarted scheduler
  drives both jobs terminal; the exported Chrome-trace JSON then
  shows ONE causally-linked trace (queue-wait -> coalesce -> compile
  -> chunk -> rollback -> resume) spanning all three dispatches, the
  per-lane imbalance rows name each tenant's straggler chip, the
  fleet latency decomposition sums to the journal-derived wall, and
  the snapshot meta carries the trace stamp.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

from fdtd3d_tpu import faults, io, jobqueue, metrics, registry, slo, \
    telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
FIX = os.path.join(ROOT, "tests", "fixtures")
V9 = os.path.join(FIX, "telemetry_v9.jsonl")


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    monkeypatch.delenv("FDTD3D_AOT_CACHE_DIR", raising=False)
    faults.clear()
    yield
    faults.clear()


def _run_tool(args, cwd=ROOT, timeout=120):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run([sys.executable] + args,
                          capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=cwd)


# -------------------------------------------------------------------------
# schema: v9 span rows validate, and ONLY at v9
# -------------------------------------------------------------------------

def test_v9_fixture_spans_are_version_gated():
    recs = telemetry.read_jsonl(V9)  # validates every record
    spans = [r for r in recs if r["type"] == "span"]
    assert {s["name"] for s in spans} >= {
        "admission", "queue_wait", "coalesce", "compile", "chunk",
        "snapshot_commit", "rollback", "resume"}
    assert all(s["t1"] >= s["t0"] for s in spans)
    # trace stamps ride the existing row types too
    start = next(r for r in recs if r["type"] == "run_start")
    assert start["trace_id"] == spans[0]["trace_id"]
    lanes = [r for r in recs if r["type"] == "batch_lane"]
    assert len({r["trace_id"] for r in lanes}) == 2  # per-lane traces
    # per-lane per-chip rows carry the lane + group join keys
    imb = next(r for r in recs if r["type"] == "imbalance")
    assert imb["lane"] == 0 and imb["group"].startswith("g-")
    # span is a v9-only record type
    with pytest.raises(ValueError, match="unknown record type"):
        telemetry.validate_record(dict(spans[0], v=8))


# -------------------------------------------------------------------------
# unit: the phase_budget SLO rule
# -------------------------------------------------------------------------

def _fixture_spans():
    return [r for r in telemetry.read_jsonl(V9) if r["type"] == "span"]


def test_phase_budget_rule_judges_span_p95():
    rule = slo.SloRule("phase-budget", "phase_budget", 300.0)
    spans = _fixture_spans()
    out = slo.evaluate_run(spans, rules=(rule,))
    assert out["results"][0]["status"] == "OK"

    # inflate queue_wait past the default 300s budget -> VIOLATION
    # naming the phase and its p95
    inflated = [dict(s) for s in spans]
    for s in inflated:
        if s["name"] == "queue_wait":
            s["t1"] = s["t0"] + 1000.0
    out = slo.evaluate_run(inflated, rules=(rule,))
    res = out["results"][0]
    assert res["status"] == "VIOLATION"
    assert "queue_wait" in res["message"]
    assert res["value"] > 300.0

    # per-phase budgets via context: a 1s queue_wait budget fires on
    # the fixture's 3.08s wait; a null budget exempts the phase
    out = slo.evaluate_run(
        spans, rules=(rule,),
        context={"phase_budgets": {"queue_wait": 1.0}})
    res = out["results"][0]
    assert res["status"] == "VIOLATION" and "queue_wait" in res["message"]
    out = slo.evaluate_run(
        inflated, rules=(rule,),
        context={"phase_budgets": {"queue_wait": None}})
    assert out["results"][0]["status"] == "OK"


def test_phase_budget_skips_pre_v9_streams():
    """Backward compat: a span-less stream must SKIP, not judge — the
    v1..v8 corpus keeps gating exactly as before."""
    rule = slo.SloRule("phase-budget", "phase_budget", 300.0)
    spanless = [r for r in telemetry.read_jsonl(V9)
                if r["type"] != "span"]
    out = slo.evaluate_run(spanless, rules=(rule,))
    res = out["results"][0]
    assert res["status"] == "SKIPPED"
    assert "span" in res["message"]


def test_slo_gate_phase_budget_exit_codes(tmp_path):
    """Acceptance: slo_gate exit 1 on an inflated queue-wait stream,
    exit 0 on the same stream with sane spans."""
    recs = telemetry.read_jsonl(V9)
    run = [r for r in recs if r["type"] in
           ("run_start", "chunk", "run_end")]
    wait = next(r for r in recs if r["type"] == "span"
                and r["name"] == "queue_wait")
    tool = os.path.join(TOOLS, "slo_gate.py")

    clean = tmp_path / "clean.jsonl"
    with open(clean, "w") as fh:
        for r in run[:1] + [wait] + run[1:]:
            fh.write(json.dumps(r) + "\n")
    proc = _run_tool([tool, str(clean)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "phase-budget" in proc.stdout

    slow = dict(wait, t1=wait["t0"] + 1000.0)
    bad = tmp_path / "slow.jsonl"
    with open(bad, "w") as fh:
        for r in run[:1] + [slow] + run[1:]:
            fh.write(json.dumps(r) + "\n")
    proc = _run_tool([tool, str(bad)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "phase-budget" in proc.stdout
    assert "VIOLATION" in proc.stdout


# -------------------------------------------------------------------------
# unit: metrics — trace-join + span-fed phase histograms
# -------------------------------------------------------------------------

def test_runs_total_is_trace_joined():
    """Two dispatches of one job share a trace_id: runs_total must
    count the LOGICAL job once, under its latest status."""
    reg = metrics.MetricsRegistry()
    base = {"v": 9, "type": "run_final", "t": 8, "steps": 8,
            "wall_s": 1.0, "mcells_per_s": 4.0}
    reg.observe_record(dict(base, run_id="r1", status="preempted",
                            trace_id="t-a"))
    reg.observe_record(dict(base, run_id="r2", status="completed",
                            trace_id="t-a"))
    reg.observe_record(dict(base, run_id="r3", status="completed"))
    rendered = reg.render()
    assert 'fdtd3d_runs_total{status="preempted"} 0' in rendered
    assert 'fdtd3d_runs_total{status="completed"} 2' in rendered


def test_phase_histograms_fill_from_v9_spans():
    reg = metrics.MetricsRegistry.from_jsonl(V9)
    rendered = reg.render()
    # queue_wait span -> queue_wait_seconds; compile span (attrs
    # compile_ms) -> compile_ms; snapshot_commit + rollback spans ->
    # their histograms.  resume is deliberately NOT recovery time.
    assert "fdtd3d_queue_wait_seconds_count 1" in rendered
    assert "fdtd3d_compile_ms_count 1" in rendered
    assert 'le="1000"' in rendered  # 700ms lands under the 1s bucket
    assert "fdtd3d_snapshot_commit_seconds_count 1" in rendered
    assert "fdtd3d_recovery_seconds_count 1" in rendered


# -------------------------------------------------------------------------
# tools: trace_export on the fixture corpus
# -------------------------------------------------------------------------

def test_trace_export_joins_fixture_streams(tmp_path):
    tool = os.path.join(TOOLS, "trace_export.py")
    out = str(tmp_path / "trace.json")
    proc = _run_tool([tool, os.path.join(FIX, "queue_v8.jsonl"),
                      "--telemetry", V9, "--out", out])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    export = json.load(open(out))
    assert export["traceEvents"]
    summ = export["fdtd3d_traces"]["t-00aa11bb22cc33dd"]
    assert summ["tenant"] == "acme"
    assert {"queue_wait", "coalesce", "compile", "chunk",
            "rollback", "resume"} <= set(summ["phases"])
    # queue phases emit flow arrows; tenants get named tracks
    evs = export["traceEvents"]
    assert any(e.get("ph") == "s" for e in evs)
    assert any(e.get("ph") == "M" and e["args"].get("name") ==
               "tenant acme" for e in evs)

    # pre-v9 streams: nothing to export, but exit 0 (not an error)
    proc = _run_tool([tool, "--telemetry",
                      os.path.join(FIX, "telemetry_v2.jsonl")])
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -------------------------------------------------------------------------
# e2e: one causally-linked trace across NaN + preempt + sched_crash
# -------------------------------------------------------------------------

def test_queue_trace_plane_e2e(tmp_path, monkeypatch):
    reg_path = str(tmp_path / "runs.jsonl")
    monkeypatch.setenv("FDTD3D_RUN_REGISTRY", reg_path)
    base = ("--3d\n--same-size 16\n--time-steps 16\n"
            "--courant-factor 0.4\n--wavelength 0.008\n"
            "--point-source Ez\n--manual-topology 2x2x2\n")
    spec_a = tmp_path / "a.txt"
    spec_a.write_text(base + "--eps 1.0\n--per-chip-telemetry\n")
    spec_b = tmp_path / "b.txt"
    spec_b.write_text(base + "--eps 2.0\n")

    q = jobqueue.JobQueue(str(tmp_path / "queue"))
    a = q.submit(str(spec_a), tenant="acme", priority=1)
    b = q.submit(str(spec_b), tenant="bravo", priority=1)
    jobs = q.jobs()
    trace_a = jobs[a]["trace_id"]
    trace_b = jobs[b]["trace_id"]
    assert trace_a.startswith("t-") and trace_a != trace_b

    # dispatch 1 = the coalesced (a, b) group: lane 1's NaN fires at
    # the t=4 chunk boundary, the whole group is preempted at t=8.
    # dispatch 2 = the group's re-dispatch (SAME traces): restores
    # the committed group snapshot, runs to t=16, then sched_crash
    # kills the scheduler before its terminal journal rows land.
    faults.install("nan@t=4,field=Ez,lane=1; preempt@t=8; "
                   "sched_crash@job=2")
    sched = jobqueue.Scheduler(q, batch_chunk=4)
    with pytest.raises(faults.SimulatedPreemption,
                       match="scheduler crashed"):
        sched.serve()
    jobs = q.jobs()
    assert jobs[a]["status"] == "running"  # crash ate the terminal row
    assert jobs[a]["trace_id"] == trace_a  # re-dispatch kept the trace
    gid = jobs[a]["group"]
    assert gid == jobs[b]["group"] and gid.startswith("g-")

    # restart: dispatch 3 resumes at t=16 (nothing left to advance)
    # and the final per-lane sweep still convicts lane 1
    faults.clear()
    out = jobqueue.Scheduler(q, batch_chunk=4).serve()
    jobs = out["jobs"]
    assert jobs[a]["status"] == "completed" and jobs[a]["t"] == 16
    assert jobs[b]["status"] == "failed"
    assert "lane 1 non-finite" in jobs[b]["reason"]
    assert jobs[a]["trace_id"] == trace_a
    assert jobs[b]["trace_id"] == trace_b

    # ---- journal: every lifecycle phase became a span on the job's
    # own trace; the re-dispatch CONTINUED it (>= 2 queue_waits, a
    # rollback naming the restored step)
    jrecs = telemetry.read_jsonl(q.journal)
    jspans = [r for r in jrecs if r["type"] == "span"]
    a_names = {s["name"] for s in jspans if s["trace_id"] == trace_a}
    assert {"admission", "queue_wait", "coalesce", "dispatch",
            "rollback", "resume"} <= a_names
    waits = [s for s in jspans
             if s["trace_id"] == trace_a and s["name"] == "queue_wait"]
    assert len(waits) >= 2
    rb = next(s for s in jspans
              if s["trace_id"] == trace_a and s["name"] == "rollback")
    assert rb["attrs"]["t_restored"] <= rb["attrs"]["t_failed"]
    # every journal row of the job carries its trace stamp
    assert all(r.get("trace_id") == trace_a for r in jrecs
               if r.get("job_id") == a)

    # ---- registry: the group's runs registered under the LEADER's
    # trace (the group run identity IS lane 0's trace)
    runs = registry.fold(registry.read(reg_path))
    g_runs = [r for r in runs.values() if r.get("job_id") == gid]
    assert g_runs and all(r.get("trace_id") == trace_a for r in g_runs)

    # ---- telemetry: executor spans + per-LANE rows in the shared
    # group stream; lane rows join each tenant's own trace
    tpath = os.path.join(q.dirpath, "groups", gid, "telemetry.jsonl")
    trecs = telemetry.read_jsonl(tpath)
    tspans = [r for r in trecs if r["type"] == "span"]
    assert {s["trace_id"] for s in tspans} == {trace_a}
    assert {"compile", "chunk", "snapshot_commit"} <= \
        {s["name"] for s in tspans}
    lanes = [r for r in trecs if r["type"] == "batch_lane"]
    assert lanes
    assert all(r["trace_id"] == trace_a for r in lanes
               if r["lane"] == 0)
    assert all(r["trace_id"] == trace_b for r in lanes
               if r["lane"] == 1)
    # per-lane imbalance names the straggler chip INSIDE the group on
    # the (2, 2, 2) mesh — one row per healthy lane, group-stamped
    start = next(r for r in trecs if r["type"] == "run_start")
    assert start["topology"] == [2, 2, 2] and start["batch"] == 2
    assert start["trace_id"] == trace_a
    imbs = [r for r in trecs if r["type"] == "imbalance"]
    lane0 = [r for r in imbs if r.get("lane") == 0]
    assert lane0 and all(r["n_chips"] == 8 for r in lane0)
    assert all(r["group"] == gid for r in imbs)
    assert any(isinstance(r.get("argmax"), int) and 0 <= r["argmax"] < 8
               for r in lane0)
    # the NaN lane's rows carry the nonfinite chip census instead
    lane1 = [r for r in imbs if r.get("lane") == 1]
    assert any(r.get("nonfinite_chips") for r in lane1)
    pcs = [r for r in trecs if r["type"] == "per_chip"]
    assert pcs and all(r["n_chips"] == 8 and r["lane"] in (0, 1)
                       for r in pcs)
    # the healthy lane's counters stay an 8-vector of real numbers
    pc0 = next(r for r in pcs if r["lane"] == 0)
    assert all(len(v) == 8 for v in pc0["counters"].values())

    # ---- checkpoint meta: the group snapshot is trace-stamped and
    # ckpt_inspect surfaces it
    snaps = sorted(glob.glob(os.path.join(q.dirpath, "groups", gid,
                                          "ckpt_t*.npz")))
    assert snaps
    meta = io.read_checkpoint_meta(snaps[-1])
    assert meta["trace_id"] == trace_a
    proc = _run_tool([os.path.join(TOOLS, "ckpt_inspect.py"),
                      snaps[-1], "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["meta"]["trace_id"] == trace_a
    proc = _run_tool([os.path.join(TOOLS, "ckpt_inspect.py"),
                      snaps[-1]])
    assert "trace_id: " + trace_a in proc.stdout

    # ---- export: ONE Chrome-trace JSON joins all three streams by
    # trace_id — queue-wait -> coalesce -> compile -> chunk ->
    # rollback -> resume on a single causally-linked timeline
    trace_json = str(tmp_path / "trace.json")
    proc = _run_tool([os.path.join(TOOLS, "trace_export.py"),
                      q.journal, "--registry", reg_path,
                      "--trace", trace_a, "--out", trace_json])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    export = json.load(open(trace_json))
    assert list(export["fdtd3d_traces"]) == [trace_a]
    summ = export["fdtd3d_traces"][trace_a]
    assert {"queue_wait", "coalesce", "compile", "chunk",
            "rollback", "resume"} <= set(summ["phases"])
    xev = [e for e in export["traceEvents"] if e.get("ph") == "X"]
    assert xev
    assert all(e["args"]["trace_id"] == trace_a for e in xev)
    assert sum(1 for e in xev if e["name"] == "queue_wait") >= 2
    assert any(e.get("ph") == "M" and e["args"].get("name") ==
               "tenant acme" for e in export["traceEvents"])

    # ---- fleet: the per-tenant latency decomposition closes — wall
    # equals the attributed phases plus the scheduler-glue residual,
    # and independently equals the journal+telemetry span envelope
    proc = _run_tool([os.path.join(TOOLS, "fleet_report.py"),
                      reg_path, "--journal", q.journal, "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rollup = json.loads(proc.stdout)
    decomp = rollup["fleet"]["latency_decomposition"]
    assert "acme" in decomp and "bravo" in decomp
    ent = decomp["acme"]
    assert {"queue_wait", "compile", "exec"} <= set(ent["phases"])
    attributed = sum(p["total_s"] for p in ent["phases"].values())
    assert ent["wall_s"] == \
        pytest.approx(attributed + ent["residual_s"], abs=1e-3)
    a_spans = [s for s in jspans + tspans if s["trace_id"] == trace_a]
    wall = max(s["t1"] for s in a_spans) - \
        min(s["t0"] for s in a_spans)
    assert ent["wall_s"] == pytest.approx(wall, abs=1e-3)

    # ---- gate: the real journal's spans pass the phase budget
    proc = _run_tool([os.path.join(TOOLS, "slo_gate.py"), q.journal])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "phase-budget" in proc.stdout
