"""Reshard-on-resume tests (ISSUE 8 tentpole piece 1).

Snapshots are topology-portable: the CPML psi recursion state is the
one topology-dependent piece of the state pytree (per-shard slab
compaction, solver.slab_axes), and io.psi_slab_expand/compact convert
it exactly between layouts. Acceptance: a run checkpointed on (2,2,2)
and resumed on (1,2,2) AND on the unsharded path finishes
BIT-IDENTICAL to the uninterrupted run (CPU, 8-device virtual mesh).

Grids here are sized so every involved topology picks the SAME
slab-vs-full storage choice (24-cell axes, pml 3/4: local extents stay
above the 2*(npml+1) slab threshold) — bit-identical continuation
across topologies additionally requires the CPML arithmetic path to
match, which it does exactly then.
"""

import os

import numpy as np
import pytest

from fdtd3d_tpu import faults, io
from fdtd3d_tpu.config import (OutputConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig)
from fdtd3d_tpu.sim import Simulation


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _cfg3d(save_dir=None, topo=None, steps=24, every=0):
    par = ParallelConfig() if topo is None else ParallelConfig(
        topology="manual", manual_topology=topo)
    out = OutputConfig()
    if save_dir is not None:
        out = OutputConfig(save_dir=str(save_dir),
                           checkpoint_every=every)
    return SimConfig(
        scheme="3D", size=(24, 24, 24), time_steps=steps, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 12)),
        parallel=par, output=out)


def _cli_argv(save_dir, topo="2x2x2", steps=24):
    argv = ["--3d", "--same-size", "24", "--time-steps", str(steps),
            "--pml-size", "3", "--use-pml", "--point-source", "Ez",
            "--courant-factor", "0.4", "--wavelength", "0.008",
            "--checkpoint-every", "8", "--save-dir", str(save_dir),
            "--log-level", "0"]
    if topo is not None:
        argv += ["--manual-topology", topo]
    return argv


# -------------------------------------------------------------------------
# psi slab layout conversion units
# -------------------------------------------------------------------------

def _slab_like(n=24, m=4, p=1, other=(6, 5)):
    """A physically-plausible psi array in the (m, p) slab layout:
    non-zero ONLY in the global boundary slabs every layout keeps."""
    rng = np.random.default_rng(0)
    full = np.zeros((n,) + other, np.float32)
    full[:m] = rng.standard_normal((m,) + other)
    full[n - m:] = rng.standard_normal((m,) + other)
    return full, io.psi_slab_compact(full, 0, p, m)


def test_psi_expand_compact_roundtrip_exact():
    n, m = 24, 4
    full, _ = _slab_like(n, m)
    for p_src in (1, 2, 3):
        src = io.psi_slab_compact(full, 0, p_src, m)
        back = io.psi_slab_expand(src, 0, n, p_src, m)
        assert np.array_equal(back, full), p_src
        for p_dst in (1, 2, 3):
            dst = io.psi_slab_compact(back, 0, p_dst, m)
            again = io.psi_slab_expand(dst, 0, n, p_dst, m)
            assert np.array_equal(again, full), (p_src, p_dst)


def test_psi_expand_full_storage_passthrough():
    full, _ = _slab_like()
    assert io.psi_slab_expand(full, 0, 24, 2, None) is full
    assert io.psi_slab_compact(full, 0, 2, None) is full


def test_psi_expand_rejects_wrong_shape():
    full, slab = _slab_like(24, 4, 2)
    with pytest.raises(ValueError, match="disagree"):
        io.psi_slab_expand(slab, 0, 24, 3, 4)   # wrong shard count
    with pytest.raises(ValueError, match="full storage"):
        io.psi_slab_expand(slab, 0, 24, 2, None)


def test_psi_compact_refuses_lossy_drop():
    """Non-zero state outside the target slabs (a snapshot disagreeing
    with its declared layout) must raise, never silently vanish."""
    full, _ = _slab_like(24, 4)
    full[5] = 1.0  # interior plane a real run never populates (and
    #                outside every slab the (m=4, p=2) target keeps)
    with pytest.raises(ValueError, match="non-zero psi planes"):
        io.psi_slab_compact(full, 0, 2, 4, key="psi_E/Ez_x")


def test_reshard_tree_validates_divisibility():
    with pytest.raises(ValueError, match="does not divide"):
        io.reshard_psi_tree({}, (24, 24, 24), (5, 1, 1), {}, (1, 1, 1),
                            {})


# -------------------------------------------------------------------------
# cross-topology restore (direct API)
# -------------------------------------------------------------------------

@pytest.mark.parametrize("dst_topo", [(1, 2, 2), None, (2, 1, 1)])
def test_checkpoint_crosses_topology_bit_exact(tmp_path, dst_topo):
    ck = str(tmp_path / "ck.npz")
    a = Simulation(_cfg3d(topo=(2, 2, 2), steps=16))
    a.advance(8)
    a.checkpoint(ck)
    a.advance(8)

    b = Simulation(_cfg3d(topo=dst_topo, steps=16))
    b.restore(ck)
    assert b.t == 8
    b.advance(8)
    for comp, ref in a.fields().items():
        assert np.array_equal(b.fields()[comp], ref), \
            f"{comp} diverged resuming on {dst_topo}"


def test_ckpt_meta_records_layout(tmp_path):
    ck = str(tmp_path / "ck.npz")
    Simulation(_cfg3d(topo=(2, 2, 2), steps=0)).checkpoint(ck)
    meta = io.read_checkpoint_meta(ck)
    assert meta["topology"] == [2, 2, 2]
    assert meta["psi_slabs"] == {"x": 4, "y": 4, "z": 4}  # npml+1


def test_restore_rejects_layout_disagreement(tmp_path):
    """A snapshot whose recorded psi slab layout contradicts what its
    topology implies is refused with a friendly CheckpointCorrupt."""
    sim = Simulation(_cfg3d(topo=(2, 2, 2), steps=0))
    ck = str(tmp_path / "ck.npz")
    sim.checkpoint(ck)
    state, extra = io.load_checkpoint(ck)
    extra["psi_slabs"] = {"x": 2, "y": 4, "z": 4}  # forged layout
    forged = str(tmp_path / "forged.npz")
    io.save_checkpoint(state, forged, extra=extra)
    other = Simulation(_cfg3d(steps=0))  # unsharded: reshard engages
    with pytest.raises(io.CheckpointCorrupt, match="slab layout"):
        other.restore(forged)


# -------------------------------------------------------------------------
# ACCEPTANCE: (2,2,2) run preempted -> resumed on (1,2,2) and unsharded,
# bit-identical to the uninterrupted run (CPU, 8-device virtual mesh)
# -------------------------------------------------------------------------

def test_cli_resume_across_topologies_bit_identical(tmp_path,
                                                    monkeypatch):
    from fdtd3d_tpu.cli import main

    # uninterrupted reference on (2,2,2)
    d_ref = tmp_path / "ref"
    assert main(_cli_argv(d_ref)) == 0
    ref, ref_extra = io.load_checkpoint(
        os.path.join(str(d_ref), "ckpt_t000024.npz"))
    assert ref_extra["topology"] == [2, 2, 2]

    for tag, topo in (("shrunk", "1x2x2"), ("unsharded", None)):
        d = tmp_path / tag
        monkeypatch.setenv("FDTD3D_FAULT_PLAN", "preempt@t=16")
        with pytest.raises(faults.SimulatedPreemption):
            main(_cli_argv(d))       # killed on (2,2,2) at t=16
        monkeypatch.delenv("FDTD3D_FAULT_PLAN")
        faults.clear()

        assert main(_cli_argv(d, topo=topo)
                    + ["--resume", "auto"]) == 0, tag
        got, extra = io.load_checkpoint(
            os.path.join(str(d), "ckpt_t000024.npz"))
        want_topo = [1, 2, 2] if topo else [1, 1, 1]
        assert extra["topology"] == want_topo, tag
        # E/H fields are layout-independent: compare them directly;
        # psi layouts differ by design — compare through the expand
        for grp in ("E", "H"):
            for comp, v in ref[grp].items():
                assert np.array_equal(got[grp][comp], v), (tag, comp)
        for grp in ("psi_E", "psi_H"):
            for key, v in ref[grp].items():
                a = _expand(v, key, ref_extra)
                b = _expand(got[grp][key], key, extra)
                assert np.array_equal(a, b), (tag, grp, key)


def _expand(arr, key, extra):
    ax = "xyz".index(key.rsplit("_", 1)[1])
    m = (extra.get("psi_slabs") or {}).get("xyz"[ax])
    return io.psi_slab_expand(np.asarray(arr), ax, 24,
                              extra["topology"][ax],
                              int(m) if m is not None else None)


# -------------------------------------------------------------------------
# friendly-error sweep: a topology that cannot map onto the devices
# -------------------------------------------------------------------------

def test_resume_oversized_topology_is_friendly_systemexit(tmp_path):
    """--resume with a decomposition needing more chips than the
    allocation has must exit with a NAMED SystemExit (mentioning the
    reshard escape hatch), never a raw mesh/shard_map traceback."""
    from fdtd3d_tpu.cli import main
    assert main(_cli_argv(tmp_path)) == 0
    ck = os.path.join(str(tmp_path), "ckpt_t000024.npz")
    with pytest.raises(SystemExit,
                       match=r"needs 64 devices.*topology-portable"):
        main(_cli_argv(tmp_path, topo="4x4x4") + ["--resume", ck])
    # and an outright invalid decomposition is named too
    with pytest.raises(SystemExit, match="invalid decomposition"):
        main(_cli_argv(tmp_path, topo="5x1x1") + ["--resume", ck])
