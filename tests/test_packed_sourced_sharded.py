"""Sharded packed kernel WITH sources (TFSF + point source).

Round-5 scope extension (VERDICT r4 missing item 2): the 48 B/cell
packed pipelined kernel must keep running under a decomposition when
the run is SOURCED — BASELINE configs #4 (Mie sphere, TFSF) and #5
(Drude nanoantenna) are the actual multi-chip validation workloads.
The E-side TFSF/point patches become traced ownership-gated plane adds
(pallas3d.Patch) and the packed H-correction algebra ships the two
cross-shard pieces by ppermute (pallas_fused._traced_patch_fix).

Runs in interpreter mode on the 8-device virtual CPU mesh; parity is
against the unsharded jnp step. A mu sphere makes db_{c} a 3D grid so
the dynamic-slice coefficient path is exercised too.
"""

import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

N = 16
# (2, 2, 2) exercises halo exchange + psi sharding on every axis at
# once and subsumes the single/two-axis cases (the round-6 ds
# precedent); those stay as slow-lane debugging decompositions.
TOPOLOGIES = [
    pytest.param((2, 1, 1), marks=pytest.mark.slow),
    pytest.param((1, 2, 2), marks=pytest.mark.slow),
    (2, 2, 2),
]


def _cfg(parallel=None, use_pallas=None, ps_pos=(5, 9, 7)):
    return SimConfig(
        scheme="3D", size=(N, N, N), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3, use_pallas=use_pallas,
        pml=PmlConfig(size=(2, 2, 2)),
        tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                        angle_teta=30.0, angle_phi=40.0, angle_psi=15.0),
        materials=MaterialsConfig(
            eps=1.0, use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True,
                                      center=(8.0, 8.0, 8.0), radius=3.0),
            mu_sphere=SphereConfig(enabled=True, center=(7.0, 8.0, 9.0),
                                   radius=3.0, value=1.5)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=ps_pos),
        parallel=parallel or ParallelConfig(),
    )


@pytest.fixture(scope="module")
def reference_fields():
    sim = Simulation(_cfg(use_pallas=False))
    sim.run()
    return sim.fields()


@pytest.mark.parametrize("topo", TOPOLOGIES)
def test_sharded_packed_with_sources(topo, reference_fields,
                                     monkeypatch):
    # round 17: the widened wedge makes sharded TFSF/Drude/grid runs
    # dispatch pallas_packed_tb by default — this test targets the
    # SINGLE-STEP kernel's patch machinery, so pin the escape hatch
    # (the round-13 test_packed_sharded_parity precedent); the tb
    # path's own sourced-sharded parity lives in
    # tests/test_pallas_packed_tb.py's widened tests
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")
    cfg = _cfg(ParallelConfig(topology="manual", manual_topology=topo),
               use_pallas=True)
    sim = Simulation(cfg)
    assert sim.mesh is not None, "sharded path not engaged"
    assert sim.step_kind == "pallas_packed", \
        f"packed kernel not engaged on {topo} (got {sim.step_kind})"
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        err = np.abs(got[comp] - ref).max()
        assert err < 1e-5 * scale, f"{comp}: {err/scale:.2e} on {topo}"


def test_psi_state_parity_sharded_sourced(monkeypatch):
    """The CPML psi recursion state must match too: the traced patch
    corrections may not leak into the slab psi stacks (the interior
    condition guarantees no psi term arises from the patches). Compared
    against the sharded jnp step on the SAME topology so the per-shard
    slab-compacted psi layouts coincide. FDTD3D_NO_TEMPORAL pinned:
    this targets the single-step kernel (round-17 note above)."""
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")
    topo = ParallelConfig(topology="manual", manual_topology=(2, 2, 2))
    ref = Simulation(_cfg(topo, use_pallas=False))
    assert ref.step_kind == "jnp"
    ref.run()
    sim = Simulation(_cfg(topo, use_pallas=True))
    assert sim.step_kind == "pallas_packed"
    sim.run()
    from fdtd3d_tpu.parallel import distributed as pdist
    for grp in ("psi_E", "psi_H"):
        for key, rv in ref.state[grp].items():
            gv = pdist.gather_to_host(sim.state[grp][key])
            rn = pdist.gather_to_host(rv)
            scale = np.abs(rn).max() + 1e-30
            assert np.abs(gv - rn).max() < 1e-5 * scale, key


def test_sharded_tb_with_sources_default_dispatch(reference_fields):
    """Round 17: the SAME oblique-TFSF + Drude + mu-grid sourced
    config under the DEFAULT dispatch — now the widened temporal-
    blocked kernel — must match the unsharded jnp reference too: the
    wedge's incident-line port under oblique incidence (teta/phi/psi
    all nonzero), its J ring, and per-cell da/db sub-blocks from the
    mu sphere, all in one run."""
    cfg = _cfg(ParallelConfig(topology="manual",
                              manual_topology=(2, 2, 2)),
               use_pallas=True)
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_tb", sim.step_kind
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        err = np.abs(got[comp] - ref).max()
        assert err < 1e-5 * scale, f"{comp}: {err/scale:.2e}"


def test_source_near_pml_falls_back():
    """A point source INSIDE the CPML guard band fails the static
    interior condition -> the sharded run must take the (fully general)
    two-pass kernels and stay correct."""
    ref = Simulation(_cfg(use_pallas=False, ps_pos=(2, 9, 7)))
    ref.run()
    cfg = _cfg(ParallelConfig(topology="manual", manual_topology=(2, 2, 2)),
               use_pallas=True, ps_pos=(2, 9, 7))
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas", \
        f"expected two-pass fallback, got {sim.step_kind}"
    sim.run()
    got = sim.fields()
    for comp, rv in ref.fields().items():
        scale = np.abs(rv).max() + 1e-30
        assert np.abs(got[comp] - rv).max() < 1e-5 * scale, comp


@pytest.mark.parametrize(
    "topo", [None, pytest.param((1, 2, 2), marks=pytest.mark.slow)])
def test_magnetic_drude_packed(topo):
    """Metamaterial mode (electric + magnetic Drude) on the packed
    kernel (round 5): K rides lag-mapped operands in the lagged H
    phase. Parity vs the jnp step, unsharded and sharded."""
    def cfg(use_pallas, parallel=None):
        c = _cfg(parallel, use_pallas)
        c.materials.use_drude_m = True
        c.materials.mu_inf = 1.5
        c.materials.omega_pm = 1e11
        c.materials.gamma_m = 1e10
        c.materials.drude_m_sphere = SphereConfig(
            enabled=True, center=(9.0, 7.0, 8.0), radius=3.0)
        return c

    ref = Simulation(cfg(False))
    assert ref.step_kind == "jnp"
    ref.run()
    par = ParallelConfig(topology="manual", manual_topology=topo) \
        if topo else None
    sim = Simulation(cfg(True, par))
    assert sim.step_kind == "pallas_packed", sim.step_kind
    sim.run()
    got = sim.fields()
    for comp, rv in ref.fields().items():
        scale = np.abs(rv).max() + 1e-30
        assert np.abs(got[comp] - rv).max() < 1e-5 * scale, comp


@pytest.mark.slow
def test_compensated_sharded_packed():
    """Compensated + sharded engages the packed kernel (round 5) and
    matches the unsharded compensated jnp step. Slow lane (tier-1 wall
    budget): tier-1 keeps compensated-packed-unsharded
    (test_compensated_packed_matches_jnp) and sharded-packed
    (test_sharded_packed_with_sources[(2,2,2)]) separately."""
    import dataclasses

    def cfg(use_pallas, parallel=None):
        c = _cfg(parallel, use_pallas)
        c.compensated = True
        c.materials = MaterialsConfig()  # comp + material grids: no-go
        return c

    ref = Simulation(cfg(False))
    ref.run()
    sim = Simulation(cfg(True, ParallelConfig(topology="manual",
                                              manual_topology=(2, 2, 2))))
    assert sim.step_kind == "pallas_packed", sim.step_kind
    sim.run()
    got = sim.fields()
    for comp, rv in ref.fields().items():
        scale = np.abs(rv).max() + 1e-30
        assert np.abs(got[comp] - rv).max() < 1e-5 * scale, comp


def test_unsharded_packed_unaffected(reference_fields, monkeypatch):
    """The unsharded packed path (static patches) still matches.
    Round 12 widened the temporal-blocked kernel to cover this config
    (TFSF runs in-kernel there — tests/test_pallas_packed_tb.py), so
    the single-step kernel's static-patch path is now reached via the
    escape hatch; it remains the tb tail/fallback and must not rot."""
    monkeypatch.setenv("FDTD3D_NO_TEMPORAL", "1")
    sim = Simulation(_cfg(use_pallas=True))
    assert sim.step_kind == "pallas_packed"
    sim.run()
    got = sim.fields()
    for comp, ref in reference_fields.items():
        scale = np.abs(ref).max() + 1e-30
        assert np.abs(got[comp] - ref).max() < 1e-5 * scale, comp
