"""Live fleet health plane (ISSUE 18 acceptance): heartbeats,
incremental tailers, and the liveness/anomaly watcher.

The load-bearing claims under test:

* LIVENESS is pure arithmetic on an injectable clock — no sleeps
  anywhere in this file. An emitter silent past ``deadline_n x
  cadence`` is ``stuck``, past 3x the deadline ``lost``; each status
  alarms exactly once (dedup per escalation), and the emitted
  ``liveness`` record is schema-v10-valid, naming the emitter and the
  last committed step t.
* RETIREMENT: silence that is the normal end of life never alarms — a
  run emitter retires once its stream's ``run_end`` landed; the
  scheduler retires once the journal folds all-terminal.
* ANOMALY: throughput EWMA under the registry-history baseline,
  queued jobs aging past the wait bound, straggler-ratio trend.
* CONTINUOUS SLO: the slo.py rules re-fire on the sliding window with
  per-rule dedup — an ongoing violation alarms once, not once per
  poll.
* E2E (chip-free): a ``sched_crash``-faulted scheduler stops
  heartbeating mid-queue and the watcher NAMES it, while a healthy
  completed run on the same poll stays green.
* ``fleet_report --follow`` rides the same cursors: a poll's cost is
  the appended bytes, not the registry size.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from fdtd3d_tpu import faults, jobqueue, metrics, telemetry, watch

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")


@pytest.fixture(autouse=True)
def _isolated_env(monkeypatch):
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    monkeypatch.delenv("FDTD3D_HEARTBEAT_S", raising=False)
    monkeypatch.delenv("FDTD3D_WATCH_INTERVAL_S", raising=False)
    faults.clear()
    yield
    faults.clear()


def _w(path, *recs):
    with open(path, "a") as fh:
        for r in recs:
            fh.write(json.dumps(r) + "\n")


def _hb(emitter, unix, seq=1, cadence=5.0, t=None, **kw):
    return {"v": 10, "type": "heartbeat", "emitter": emitter,
            "pid": 123, "host": "h0", "seq": seq, "unix": unix,
            "t": t, "cadence_s": cadence, **kw}


def _run_start(**kw):
    rec = {"v": 10, "type": "run_start", "wall_time": "2026-08-07",
           "git_sha": "deadbeef", "jax_version": "0.4.37",
           "platform": "cpu", "device_kind": "cpu", "hbm_gbps": None,
           "step_kind": "jnp", "grid": [16, 16, 16],
           "dtype": "float32"}
    rec.update(kw)
    return rec


def _chunk(t, mcps):
    return {"v": 10, "type": "chunk", "chunk": t // 4, "t": t,
            "steps": 4, "wall_s": 0.5, "mcells_per_s": mcps,
            "energy": 1e-27, "div_l2": 0.01, "div_linf": 0.1,
            "max_e": 1e-4, "max_h": 1e-7, "finite": True,
            "vmem_rung": 0}


def _run_end(t):
    return {"v": 10, "type": "run_end", "t": t, "steps": t,
            "wall_s": 1.0, "mcells_per_s": 5.0,
            "first_unhealthy_t": None}


def _watcher(now, **kw):
    """FleetWatcher on a mutable injected clock (a 1-element list)."""
    return watch.FleetWatcher(clock=lambda: now[0], **kw)


# -------------------------------------------------------------------------
# liveness: deadline math, escalation, dedup
# -------------------------------------------------------------------------

def test_watch_interval_bad_values_are_named(monkeypatch):
    monkeypatch.setenv("FDTD3D_WATCH_INTERVAL_S", "soon")
    with pytest.raises(ValueError, match="FDTD3D_WATCH_INTERVAL_S='soon'"):
        watch.watch_interval_s()
    monkeypatch.setenv("FDTD3D_WATCH_INTERVAL_S", "0")
    with pytest.raises(ValueError, match="must be > 0"):
        watch.watch_interval_s()


def test_liveness_stuck_then_lost_alarms_once_per_status(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _w(p, _run_start(), _hb("run", 1000.0, cadence=5.0, t=4,
                            run_id="r1"))
    now = [1005.0]
    w = _watcher(now, telemetry=[p], interval_s=10.0)
    # inside the deadline (3 x 5s = 15s): green
    assert w.poll_once()["liveness"] == []
    # past the deadline: stuck, once — the second poll at the same
    # status is deduped
    now[0] = 1020.0
    rep = w.poll_once()
    assert [r["status"] for r in rep["liveness"]] == ["stuck"]
    rec = rep["liveness"][0]
    telemetry.validate_record(rec)  # schema-v10-valid as emitted
    assert rec["v"] == telemetry.SCHEMA_VERSION
    assert rec["emitter"] == "run" and rec["last_t"] == 4
    assert rec["run_id"] == "r1"
    assert rec["silent_s"] == pytest.approx(20.0)
    assert rec["deadline_s"] == pytest.approx(15.0)
    assert w.poll_once()["liveness"] == []
    # past 3 x deadline: the escalation to lost fires exactly once
    now[0] = 1050.0
    assert [r["status"] for r in w.poll_once()["liveness"]] == ["lost"]
    assert w.poll_once()["liveness"] == []
    # a fresh beat re-arms the emitter
    _w(p, _hb("run", 1050.0, seq=2, cadence=5.0, t=8))
    now[0] = 1052.0
    rep = w.poll_once()
    assert rep["liveness"] == []
    assert [e["seq"] for e in rep["emitters"]] == [2]


def test_liveness_cadence_zero_uses_watch_interval(tmp_path):
    """FDTD3D_HEARTBEAT_S=0 (every-boundary mode) declares cadence 0;
    the watcher's own poll interval is the deadline base then."""
    p = str(tmp_path / "t.jsonl")
    _w(p, _run_start(), _hb("run", 1000.0, cadence=0.0))
    now = [1025.0]
    w = _watcher(now, telemetry=[p], interval_s=10.0)  # deadline 30
    assert w.poll_once()["liveness"] == []
    now[0] = 1035.0
    assert [r["status"] for r in w.poll_once()["liveness"]] == \
        ["stuck"]


def test_liveness_retires_on_run_end(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _w(p, _run_start(), _hb("run", 1000.0, cadence=5.0, t=8),
       _run_end(8))
    now = [999999.0]  # arbitrarily far in the future
    rep = _watcher(now, telemetry=[p]).poll_once()
    assert rep["liveness"] == []
    assert rep["emitters"][0]["retired"] is True


def test_scheduler_retires_only_when_journal_all_terminal(tmp_path):
    j = str(tmp_path / "journal.jsonl")
    submit = {"v": 10, "type": "job_submit", "job_id": "j1",
              "tenant": "acme", "spec": "a.txt", "priority": 0,
              "cells": 4096, "status": "queued",
              "wall_time": "2026-08-07", "unix": 1000.0}
    running = {"v": 10, "type": "job_state", "job_id": "j1",
               "tenant": "acme", "status": "running", "unix": 1001.0}
    _w(j, submit, running, _hb("scheduler", 1001.0, cadence=5.0))
    now = [999999.0]
    w = _watcher(now, journal=j)
    rep = w.poll_once()
    # a job is still non-terminal: the silent scheduler is LOST
    assert [r["status"] for r in rep["liveness"]] == ["lost"]
    assert rep["liveness"][0]["emitter"] == "scheduler"
    # ...until the journal folds terminal — then silence is normal
    done = {"v": 10, "type": "job_state", "job_id": "j1",
            "tenant": "acme", "status": "completed", "unix": 1002.0}
    _w(j, done)
    rep = w.poll_once()
    assert rep["liveness"] == []
    assert rep["emitters"][0]["retired"] is True


def test_scheduler_retirement_is_per_identity_on_leased_journal(
        tmp_path):
    """Schema v11: on a journal carrying lease rows, a scheduler
    emitter retires iff its pid+host no longer holds the ACTIVE
    (highest-token, unreleased) lease — a fenced-out dead peer goes
    quiet without alarming, while the live holder still alarms when
    it stops beating, even with every job terminal."""
    j = str(tmp_path / "journal.jsonl")
    submit = {"v": 11, "type": "job_submit", "job_id": "j1",
              "tenant": "acme", "spec": "a.txt", "priority": 0,
              "cells": 4096, "status": "queued",
              "wall_time": "2026-08-07", "unix": 1000.0}
    lease = {"pid": 123, "host": "h0", "start": 900.0,
             "unix": 900.0, "ttl_s": 30.0}
    acq0 = {"v": 11, "type": "lease_acquire", "sched": "h0:123:900",
            "token": 1, **lease}
    acq1 = {"v": 11, "type": "lease_acquire", "sched": "h0:124:950",
            "token": 2, "takeover_from": "h0:123:900",
            **{**lease, "pid": 124, "start": 950.0, "unix": 950.0}}
    done = {"v": 11, "type": "job_state", "job_id": "j1",
            "tenant": "acme", "status": "completed", "unix": 1002.0,
            "fence": 2, "sched": "h0:124:950"}
    # a stale row from the fenced-out scheduler rides along: rejected
    stale = {"v": 11, "type": "job_state", "job_id": "j1",
             "tenant": "acme", "status": "running", "unix": 1001.0,
             "fence": 1, "sched": "h0:123:900"}
    _w(j, submit, acq0, acq1, stale, done,
       _hb("scheduler", 1000.0, pid=123),
       _hb("scheduler", 1000.0, pid=124))
    now = [999999.0]
    w = _watcher(now, journal=j)
    rep = w.poll_once()
    # the fenced-out identity (pid 123) retired silently; the active
    # holder (pid 124) is LOST — even though the journal folds
    # all-terminal (the legacy rule would have retired both)
    assert [r["status"] for r in rep["liveness"]] == ["lost"]
    assert rep["liveness"][0]["pid"] == 124
    by_pid = {e["pid"]: e["retired"] for e in rep["emitters"]}
    assert by_pid == {123: True, 124: False}
    # the lease fold + fencing surface on the report
    assert [(lz["token"], lz["active"]) for lz in rep["leases"]] == \
        [(1, False), (2, True)]
    assert rep["stale_rejected"] == 1
    # the stale running row did not overwrite the accepted completed
    assert w._jobs["j1"]["status"] == "completed"
    text = watch.format_report(rep)
    assert "LEASE h0:124:950 token=2 active" in text
    assert "STALE 1 fenced-out" in text
    # once the active holder RELEASES, its silence is normal too
    rel = {"v": 11, "type": "lease_release", "sched": "h0:124:950",
           "token": 2, **{**lease, "pid": 124, "start": 950.0,
                          "unix": 1003.0, "ttl_s": 0.0}}
    _w(j, rel)
    rep = w.poll_once()
    assert rep["liveness"] == []
    assert all(e["retired"] for e in rep["emitters"])


# -------------------------------------------------------------------------
# anomaly: EWMA drift, queue-wait aging, straggler trend
# -------------------------------------------------------------------------

def test_anomaly_throughput_drift_vs_registry_baseline(tmp_path):
    reg = str(tmp_path / "runs.jsonl")
    p = str(tmp_path / "t.jsonl")
    # history: completed runs on the same (step_kind, grid, dtype)
    # key at ~10 Mcells/s
    for i, mcps in enumerate((9.0, 10.0, 11.0)):
        _w(reg, {"v": 10, "type": "run_begin", "run_id": f"r{i}",
                 "kind": "begin", "status": "running",
                 "git_sha": "deadbeef", "platform": "cpu",
                 "wall_time": "2026-08-07", "step_kind": "jnp",
                 "grid": [16, 16, 16], "dtype": "float32"},
           {"v": 10, "type": "run_final", "run_id": f"r{i}",
            "status": "completed", "t": 8, "steps": 8, "wall_s": 1.0,
            "mcells_per_s": mcps})
    # live stream: same key crawling at 2 Mcells/s
    _w(p, _run_start(), _chunk(4, 2.0), _chunk(8, 2.0))
    now = [2000.0]
    rep = _watcher(now, registry=reg, telemetry=[p]).poll_once()
    drift = [a for a in rep["anomalies"]
             if a["kind"] == "throughput_drift"]
    assert len(drift) == 1
    assert drift[0]["baseline_mcells_per_s"] == pytest.approx(10.0)
    assert drift[0]["ewma_mcells_per_s"] == pytest.approx(2.0)
    # a healthy stream on the same baseline stays quiet
    p2 = str(tmp_path / "t2.jsonl")
    _w(p2, _run_start(), _chunk(4, 9.5), _chunk(8, 10.5))
    rep2 = _watcher(now, registry=reg, telemetry=[p2]).poll_once()
    assert [a for a in rep2["anomalies"]
            if a["kind"] == "throughput_drift"] == []


def test_anomaly_queue_wait_aging(tmp_path):
    j = str(tmp_path / "journal.jsonl")
    _w(j, {"v": 10, "type": "job_submit", "job_id": "j9",
           "tenant": "acme", "spec": "a.txt", "priority": 0,
           "cells": 4096, "status": "queued",
           "wall_time": "2026-08-07", "unix": 1000.0})
    now = [1100.0]
    w = _watcher(now, journal=j, queue_wait_max_s=50.0)
    aging = [a for a in w.poll_once()["anomalies"]
             if a["kind"] == "queue_wait_aging"]
    assert len(aging) == 1
    assert aging[0]["job_id"] == "j9"
    assert aging[0]["wait_s"] == pytest.approx(100.0)


def test_anomaly_straggler_trend(tmp_path):
    p = str(tmp_path / "t.jsonl")
    imb = {"v": 10, "type": "imbalance", "chunk": 1, "t": 4,
           "metric": "wall_s", "max": 3.0, "mean": 1.0, "ratio": 3.0,
           "argmax": 2, "n_chips": 4}
    _w(p, _run_start(), imb)
    now = [2000.0]
    rep = _watcher(now, telemetry=[p], straggler_max=2.0).poll_once()
    trend = [a for a in rep["anomalies"]
             if a["kind"] == "straggler_trend"]
    assert len(trend) == 1
    assert trend[0]["ratio_ewma"] == pytest.approx(3.0)


# -------------------------------------------------------------------------
# continuous SLO: sliding window + per-rule dedup
# -------------------------------------------------------------------------

def test_slo_ongoing_violation_alarms_once(tmp_path):
    p = str(tmp_path / "t.jsonl")
    retry = {"v": 10, "type": "retry", "t": 4, "attempt": 1,
             "delay_s": 0.0, "error": "boom", "chip": None, "host": 0}
    _w(p, _run_start(), _chunk(4, 5.0), retry, retry, retry,
       _chunk(8, 5.0))
    now = [2000.0]
    w = _watcher(now, telemetry=[p])
    rep = w.poll_once()
    rules = [a["rule"] for a in rep["alerts"]]
    assert "recovery-rate" in rules
    assert list(rep["slo"].values()) == ["VIOLATION"]
    # nothing new appended: the ongoing violation does NOT re-alarm,
    # and alerts_total holds still
    fired = w.metrics.value("alerts_total", rule="recovery-rate")
    assert fired == 1.0
    assert w.poll_once()["alerts"] == []
    assert w.metrics.value("alerts_total",
                           rule="recovery-rate") == fired


# -------------------------------------------------------------------------
# plumbing: incremental drain, cursor resume, exposition refresh
# -------------------------------------------------------------------------

def test_poll_is_incremental_and_cursor_resumes(tmp_path):
    p = str(tmp_path / "t.jsonl")
    cur = str(tmp_path / "cursor.json")
    _w(p, _run_start(), _chunk(4, 5.0))
    now = [2000.0]
    w = _watcher(now, telemetry=[p], cursor_path=cur)
    assert w.poll_once()["records"] == 2
    assert w.poll_once()["records"] == 0  # nothing appended
    _w(p, _chunk(8, 5.0))
    assert w.poll_once()["records"] == 1
    # a restarted watcher resumes from the committed cursor: zero
    # records re-read, zero bytes re-paid
    w2 = _watcher(now, telemetry=[p], cursor_path=cur)
    assert w2.poll_once()["records"] == 0
    assert w2.tailer.bytes_read == 0


def test_invalid_record_degrades_to_named_event(tmp_path):
    p = str(tmp_path / "t.jsonl")
    _w(p, _run_start(), {"v": 10, "type": "no_such_type"})
    now = [2000.0]
    rep = _watcher(now, telemetry=[p]).poll_once()
    assert rep["records"] == 1  # the valid row still landed
    assert any("invalid record" in e for e in rep["events"])


def test_metrics_exposition_refreshes_per_poll(tmp_path):
    p = str(tmp_path / "t.jsonl")
    prom = str(tmp_path / "watch.prom")
    _w(p, _run_start(), _hb("run", 1000.0, cadence=5.0, t=4))
    now = [1002.0]
    w = _watcher(now, telemetry=[p], metrics_path=prom)
    w.poll_once()
    text = open(prom).read()
    assert 'heartbeats_total{emitter="run"} 1' in text
    assert "fdtd3d_watch_last_poll_unix 1002" in text
    assert text.endswith("# EOF\n")
    _w(p, _hb("run", 1003.0, seq=2, cadence=5.0, t=8))
    now[0] = 1004.0
    w.poll_once()
    assert 'heartbeats_total{emitter="run"} 2' in open(prom).read()


def test_liveness_records_append_to_out_path(tmp_path):
    p = str(tmp_path / "t.jsonl")
    out = str(tmp_path / "watch_out.jsonl")
    _w(p, _run_start(), _hb("run", 1000.0, cadence=5.0, t=4))
    now = [999999.0]
    w = _watcher(now, telemetry=[p], out_path=out)
    rep = w.poll_once()
    assert [r["status"] for r in rep["liveness"]] == ["lost"]
    rows = telemetry.read_jsonl(out)  # validates every row
    assert [r["type"] for r in rows] == ["liveness"]


# -------------------------------------------------------------------------
# e2e (chip-free): crashed scheduler is NAMED, healthy run stays green
# -------------------------------------------------------------------------

def test_e2e_crashed_scheduler_named_healthy_run_green(tmp_path,
                                                       monkeypatch):
    """The acceptance loop: FDTD3D_HEARTBEAT_S=0 turns on every-
    boundary heartbeats; a sched_crash fault kills the scheduler
    BEFORE its first job's post-run journal row (job left "running",
    beats stop); a separate healthy run completes normally. One
    watcher poll far in the future flags exactly the scheduler — the
    finished run's emitter retires instead of alarming."""
    monkeypatch.setenv("FDTD3D_HEARTBEAT_S", "0")
    spec = tmp_path / "a.txt"
    spec.write_text("--3d\n--same-size 12\n--time-steps 8\n"
                    "--courant-factor 0.4\n--wavelength 0.008\n")
    q = jobqueue.JobQueue(str(tmp_path / "queue"))
    job = q.submit(str(spec), tenant="acme")
    faults.install("sched_crash@job=1")
    sched = jobqueue.Scheduler(q)
    with pytest.raises(faults.SimulatedPreemption,
                       match="scheduler crashed"):
        sched.serve()
    faults.clear()

    # the journal now interleaves scheduler heartbeats with job rows —
    # and the queue fold is UNAFFECTED by them: the crash left the job
    # mid-flight
    jobs = q.jobs()
    assert jobs[job]["status"] == "running"
    beats = [r for r in telemetry.read_jsonl(q.journal)
             if r["type"] == "heartbeat"]
    assert beats and all(b["emitter"] == "scheduler" for b in beats)
    last_beat = max(b["unix"] for b in beats)

    # a healthy run, heartbeating at every chunk boundary, completes
    from fdtd3d_tpu.config import (OutputConfig, PmlConfig,
                                   PointSourceConfig, SimConfig)
    from fdtd3d_tpu.sim import Simulation
    stream = str(tmp_path / "healthy.jsonl")
    sim = Simulation(SimConfig(
        scheme="3D", size=(16, 16, 16), time_steps=8, dx=1e-3,
        courant_factor=0.4, wavelength=8e-3,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(8, 8, 8)),
        output=OutputConfig(telemetry_path=stream)))
    sim.advance(4)
    sim.advance(4)
    sim.close_telemetry()
    assert any(r["type"] == "heartbeat" and r["emitter"] == "run"
               for r in telemetry.read_jsonl(stream))

    # one poll, clock injected past the deadline (cadence 0 beats use
    # the watcher interval, 5s -> deadline 15s): the dead scheduler is
    # STUCK by name with its last beat time; the finished run retired
    now = [last_beat + 16.0]
    w = _watcher(now, journal=q.journal, telemetry=[stream],
                 interval_s=5.0)
    rep = w.poll_once()
    assert [(r["emitter"], r["status"]) for r in rep["liveness"]] == \
        [("scheduler", "stuck")]
    assert rep["liveness"][0]["last_unix"] == pytest.approx(last_beat)
    by_emitter = {e["emitter"]: e for e in rep["emitters"]}
    assert by_emitter["run"]["retired"] is True
    assert by_emitter["scheduler"]["retired"] is False

    # the CLI drives the same loop: exit 1, scheduler named in text
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "fleet_watch.py"),
         "--journal", q.journal, "--telemetry", stream,
         "--once", "--now", str(last_beat + 16.0), "--interval", "5"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=ROOT)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "LIVENESS STUCK" in proc.stdout
    assert "scheduler" in proc.stdout


# -------------------------------------------------------------------------
# fleet_report --follow rides the same cursors (satellite)
# -------------------------------------------------------------------------

def _load_fleet_report():
    spec = importlib.util.spec_from_file_location(
        "fleet_report", os.path.join(TOOLS, "fleet_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_report_follow_poll_cost_is_the_delta(tmp_path):
    """--follow's FollowState: after the initial fold, re-polling a
    grown registry costs the appended bytes — NOT another full scan
    that re-scales with file size."""
    fr = _load_fleet_report()
    reg = str(tmp_path / "runs.jsonl")

    def _run_rows(i):
        return ({"v": 10, "type": "run_begin", "run_id": f"r{i}",
                 "kind": "begin", "status": "running",
                 "git_sha": "deadbeef", "platform": "cpu",
                 "wall_time": "2026-08-07"},
                {"v": 10, "type": "run_final", "run_id": f"r{i}",
                 "status": "completed", "t": 8, "steps": 8,
                 "wall_s": 1.0, "mcells_per_s": 5.0})

    for i in range(200):
        _w(reg, *_run_rows(i))
    st = fr.FollowState(reg)
    roll = st.poll(force=True)
    assert roll["fleet"]["by_status"] == {"completed": 200}
    cost_initial = st.tailer.bytes_read
    assert cost_initial >= os.path.getsize(reg)  # first fold pays all

    # no growth -> no re-fold at all
    assert st.poll() is None

    # one appended run -> the poll pays ~2 rows, not 200 re-read
    _w(reg, *_run_rows(200))
    roll = st.poll()
    assert roll["fleet"]["by_status"] == {"completed": 201}
    delta = st.tailer.bytes_read - cost_initial
    assert 0 < delta <= len("".join(
        json.dumps(r) + "\n" for r in _run_rows(200))) + 1
    assert delta < cost_initial / 50  # does not re-scale with size
