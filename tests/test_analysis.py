"""The unified static-analysis pass (ISSUE 9 acceptance).

Load-bearing claims under test:

* the FULL rule set is CLEAN over the repo with an empty suppression
  baseline — tier-1's zero-tolerance gate (the CLI form is smoked in
  tests/test_tools_cli.py);
* every rule demonstrably FIRES on its checked-in known-bad fixture
  (tests/fixtures/lint/) — no rule can go vacuously green;
* scope coverage is reported as an ENUMERATED 0 unscoped collectives
  (not a percentage) for every sharded step kind on the (2,2,2) CPU
  mesh;
* the env-knob registry (config.ENV_KNOBS) covers the previously
  undeclared knobs and stays read-alive both ways;
* the suppression baseline is schema-checked, requires per-entry
  reasons, and actually suppresses;
* the --json report round-trips.
"""

import json
import os

import pytest

from fdtd3d_tpu.analysis import (REPORT_SCHEMA, Context, Finding,
                                 apply_baseline, load_baseline,
                                 run_rules, rules_by_name)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(ROOT, "tests", "fixtures", "lint")

AST_RULES = ("no-bare-print", "atomic-write", "env-registry",
             "tracer-hostility", "exception-hygiene")
STRUCTURAL_RULES = ("schema-drift", "donation-safety",
                    "scope-coverage", "readback-discipline")


def _fixture_ctx(fname, label=None):
    path = os.path.join(FIX, fname)
    return Context(root=FIX, paths=[(label or fname, path)])


def _fmt(findings):
    return "\n".join(f["message"] if isinstance(f, dict) else f.format()
                     for f in findings)


# -------------------------------------------------------------------------
# the repo is clean (zero-tolerance gate)
# -------------------------------------------------------------------------

def test_registry_covers_both_engines():
    names = set(rules_by_name())
    assert names == set(AST_RULES) | set(STRUCTURAL_RULES)


def test_ast_rules_clean_over_repo():
    rep = run_rules(list(AST_RULES))
    assert rep["clean"], _fmt(rep["findings"])


@pytest.fixture(scope="module")
def structural_report():
    """One run of the heavy rules (module-scoped: the scope rule
    traces all four sharded kinds; readback drives a real sim)."""
    return run_rules(list(STRUCTURAL_RULES))


def test_structural_rules_clean_over_repo(structural_report):
    assert structural_report["clean"], _fmt(
        structural_report["findings"])


def test_scope_coverage_is_enumerated_zero(structural_report):
    """ISSUE 9 acceptance: 0 unscoped collectives (a COUNT, not a
    percentage) for every sharded step kind on the (2,2,2) mesh."""
    from fdtd3d_tpu import costs
    stats = structural_report["rules"]["scope-coverage"]["stats"]
    # + the round-14 widened sharded tb lane (TFSF/Drude/grid wedge)
    # + the round-16 sharded BATCHED packed lane (the batch's ONE
    #   shared halo exchange per step must be mesh-scoped too)
    assert set(stats) == set(costs.SHARDED_STEP_KINDS) \
        | {"pallas_packed_tb_widened", "pallas_packed_batch"}
    for kind, row in stats.items():
        assert row["unscoped_collectives"] == 0, (kind, row)
        assert row["collectives"] > 0, (kind, row)   # lane not empty


def test_donation_rule_covered_every_kernel(structural_report):
    stats = structural_report["rules"]["donation-safety"]["stats"]
    assert set(stats) == {"pallas", "pallas_fused", "pallas_packed",
                          "pallas_packed_tb",
                          "pallas_packed_tb_widened",
                          "pallas_packed_ds",
                          "pallas_packed_batch"}
    for label, row in stats.items():
        assert row["aliased_operands"] > 0, (label, row)


def test_readback_budget_reported(structural_report):
    stats = structural_report["rules"]["readback-discipline"]["stats"]
    assert stats["device_gets_per_chunk"] == 1
    assert stats["max_leaf_elems"] <= 8


# -------------------------------------------------------------------------
# every rule fires on its known-bad fixture (rules proven live)
# -------------------------------------------------------------------------

def test_no_bare_print_fires_on_fixture():
    from fdtd3d_tpu.analysis.ast_rules import NoBarePrintRule
    findings, _ = NoBarePrintRule().run(_fixture_ctx("bad_print.py"))
    assert len(findings) == 1 and "print" in findings[0].message


def test_atomic_write_fires_on_fixture():
    from fdtd3d_tpu.analysis.ast_rules import AtomicWriteRule
    ctx = _fixture_ctx("bad_write.py", "fdtd3d_tpu/bad_write.py")
    findings, _ = AtomicWriteRule().run(ctx)
    msgs = _fmt(findings)
    assert "open(..., 'w')" in msgs
    assert ".tofile()" in msgs


def test_env_registry_fires_on_fixture():
    from fdtd3d_tpu.analysis.ast_rules import EnvRegistryRule
    findings, _ = EnvRegistryRule().run(_fixture_ctx("bad_env.py"))
    msgs = _fmt(findings)
    assert "FDTD3D_NOT_IN_REGISTRY" in msgs
    assert "FDTD3D_ALSO_UNDECLARED" in msgs   # os.getenv form too


def test_tracer_hostility_fires_on_fixture():
    from fdtd3d_tpu.analysis.ast_rules import TracerHostilityRule
    findings, _ = TracerHostilityRule().run(
        _fixture_ctx("bad_tracer.py"))
    msgs = _fmt(findings)
    assert "time.time()" in msgs
    # transitively reached helper, not just the marked root:
    assert "float()" in msgs and "'helper'" in msgs


def test_exception_hygiene_fires_on_fixture():
    from fdtd3d_tpu.analysis.ast_rules import ExceptionHygieneRule
    findings, _ = ExceptionHygieneRule().run(
        _fixture_ctx("bad_except.py"))
    msgs = _fmt(findings)
    assert "bare 'except:'" in msgs
    assert "BaseException" in msgs


def test_exception_hygiene_sees_raise_past_nested_defs(tmp_path):
    """Regression: a re-raise AFTER a lambda/def inside the same
    handler statement must still count (the scan skips nested-def
    subtrees, it does not abort on them)."""
    from fdtd3d_tpu.analysis.ast_rules import ExceptionHygieneRule
    p = tmp_path / "ok.py"
    p.write_text(
        "def f(ctx, fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except BaseException:\n"
        "        with ctx(on_err=lambda: None):\n"
        "            raise\n")
    ctx = Context(root=str(tmp_path), paths=[("ok.py", str(p))])
    findings, _ = ExceptionHygieneRule().run(ctx)
    assert not findings, _fmt(findings)
    # ...while a raise ONLY inside the nested lambda/def still flags
    p2 = tmp_path / "bad.py"
    p2.write_text(
        "def f(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    except BaseException:\n"
        "        cb = lambda: (_ for _ in ()).throw(ValueError())\n"
        "        return cb\n")
    ctx2 = Context(root=str(tmp_path), paths=[("bad.py", str(p2))])
    findings2, _ = ExceptionHygieneRule().run(ctx2)
    assert findings2 and "BaseException" in findings2[0].message


def test_donation_unintrospectable_alias_is_a_finding():
    """Regression: an aliased pallas_call whose grid/specs kwargs are
    not retrievable must FAIL the gate (unverifiable), never silently
    pass — the rule cannot go vacuously green on a call-form change."""
    from fdtd3d_tpu.analysis.graph_rules import check_pallas_capture
    probs = check_pallas_capture(
        "mystery", {"input_output_aliases": {0: 0}})
    assert probs and "unverifiable" in probs[0], probs


def test_schema_drift_fires_on_fixture():
    from fdtd3d_tpu.analysis.schema_rules import SchemaDriftRule
    findings, _ = SchemaDriftRule().run(_fixture_ctx("bad_schema.py"))
    msgs = _fmt(findings)
    assert "'extra_mystery'" in msgs          # literal kwarg
    assert "'sneaky_extra'" in msgs           # **expansion, resolved
    assert "'undeclared_lane'" in msgs        # dict-literal record


def test_donation_safety_fires_on_fixture():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bad_kernel", os.path.join(FIX, "bad_kernel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fdtd3d_tpu.analysis.graph_rules import check_pallas_capture
    probs = check_pallas_capture("bad", mod.bad_capture())
    assert any("donation hazard" in p for p in probs), probs
    probs2 = check_pallas_capture("bad2", mod.nonmonotone_capture())
    assert any("NON-MONOTONE" in p for p in probs2), probs2


def test_donation_safety_fires_on_depth_k_fixture():
    """ISSUE-11 satellite: the depth-k known-bad fixture — a k=3
    pipeline whose H-family output lost its 2k-1 lag (fetch lands one
    iteration after the aliased output's first visit), and a lag-4
    in-map missing the drain-iteration clamp (non-monotone fetches) —
    must fire the generalized donation-safety check."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bad_kernel_tb_k", os.path.join(FIX, "bad_kernel_tb_k.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fdtd3d_tpu.analysis.graph_rules import check_pallas_capture
    probs = check_pallas_capture("tb_k", mod.bad_lag_capture())
    assert any("donation hazard" in p for p in probs), probs
    probs2 = check_pallas_capture("tb_k2",
                                  mod.unclamped_drain_capture())
    assert any("NON-MONOTONE" in p for p in probs2), probs2


def test_donation_safety_fires_on_batched_fixture():
    """Round-16 satellite: the lane-capable batched build's known-bad
    fixture — a donated packed operand re-reading block i-1 under the
    batch_lane-surcharged (smaller-tile, more-blocks) grid, and a
    backward-walking donated in-map — must fire the generalized
    donation-safety check; and the REAL batched build
    (make_packed_eh_step_batched, registered as pallas_packed_batch)
    must capture cleanly."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bad_kernel_batch", os.path.join(FIX, "bad_kernel_batch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fdtd3d_tpu.analysis.graph_rules import (_KERNEL_TARGETS,
                                                 _target_config,
                                                 capture_kernel_calls,
                                                 check_pallas_capture)
    probs = check_pallas_capture("batch",
                                 mod.stale_fetch_capture())
    assert any("donation hazard" in p for p in probs), probs
    probs2 = check_pallas_capture("batch2",
                                  mod.nonmonotone_capture())
    assert any("NON-MONOTONE" in p for p in probs2), probs2
    # the real build is registered and passes the same check
    targets = {lbl: (m, b) for lbl, m, b in _KERNEL_TARGETS}
    assert targets["pallas_packed_batch"] == \
        ("fdtd3d_tpu.ops.pallas_packed", "make_packed_eh_step_batched")
    import importlib

    from fdtd3d_tpu.solver import build_static
    modname, builder = targets["pallas_packed_batch"]
    cfg, topo = _target_config("pallas_packed_batch")
    assert topo is None
    calls = capture_kernel_calls(importlib.import_module(modname),
                                 builder, build_static(cfg))
    assert calls
    for kw in calls:
        assert check_pallas_capture("pallas_packed_batch", kw) == []


def test_scope_coverage_fires_on_fixture():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bad_scope", os.path.join(FIX, "bad_scope.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fdtd3d_tpu.analysis.graph_rules import (collect_collectives,
                                                 unscoped_collectives)
    colls = collect_collectives(mod.build_unscoped_jaxpr().jaxpr)
    assert [x for x in unscoped_collectives(colls)
            if x[0] == "ppermute"], colls


def test_scope_coverage_fires_on_sharded_tb_fixture():
    """ISSUE-10 satellite: the sharded-tb known-bad fixture — a
    depth-2 ghost gather whose stacked two-plane ppermute inherits the
    packed-kernel-tb family scope instead of naming halo-exchange —
    must fire the rule (one unscoped ppermute, attributed to the
    family scope)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bad_scope_tb", os.path.join(FIX, "bad_scope_tb.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fdtd3d_tpu.analysis.graph_rules import (collect_collectives,
                                                 unscoped_collectives)
    colls = collect_collectives(
        mod.build_unscoped_tb_gather_jaxpr().jaxpr)
    bad = [x for x in unscoped_collectives(colls)
           if x[0] == "ppermute"]
    assert bad and bad[0][1] == "packed-kernel-tb", (colls, bad)


def test_scope_coverage_rejects_inherited_outer_scope():
    """E2E-found regression: a ppermute that merely INHERITS an outer
    E-update scope (its own halo-exchange scope stripped) is a
    mis-attributed exchange and must fail the bar — 'any scope' was
    too weak to catch a silently de-scoped halo exchange."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from fdtd3d_tpu.analysis.graph_rules import (collect_collectives,
                                                 unscoped_collectives)
    from fdtd3d_tpu.parallel.mesh import shard_map_compat
    from fdtd3d_tpu.telemetry import named

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))

    def exchange(x):
        with named("E-update"):   # outer family scope only
            return jax.lax.ppermute(x, "x", [(0, 1), (1, 0)])

    f = shard_map_compat(exchange, mesh, in_specs=(P("x"),),
                         out_specs=P("x"))
    colls = collect_collectives(
        jax.make_jaxpr(f)(jnp.ones((4, 4), jnp.float32)).jaxpr)
    bad = unscoped_collectives(colls)
    assert bad and bad[0][0] == "ppermute" \
        and bad[0][1] == "E-update", (colls, bad)
    # and a properly-scoped exchange passes

    def good(x):
        with named("halo-exchange"):
            return jax.lax.ppermute(x, "x", [(0, 1), (1, 0)])

    g = shard_map_compat(good, mesh, in_specs=(P("x"),),
                         out_specs=P("x"))
    colls2 = collect_collectives(
        jax.make_jaxpr(g)(jnp.ones((4, 4), jnp.float32)).jaxpr)
    assert not unscoped_collectives(colls2), colls2


def test_readback_discipline_fires_on_fixture():
    from fdtd3d_tpu.analysis.graph_rules import check_transfer_log
    with open(os.path.join(FIX, "bad_readback.json")) as f:
        bad = json.load(f)
    probs = check_transfer_log(bad["calls"], bad["n_chunks"])
    assert any("full-field" in p for p in probs), probs
    assert any("<=1 scalar-tuple" in p for p in probs), probs
    # and the budget-compliant log passes
    assert not check_transfer_log([[1] * 6], 1)


# -------------------------------------------------------------------------
# env-knob registry content (ISSUE 9 satellite)
# -------------------------------------------------------------------------

def test_env_registry_declares_the_former_strays():
    """The knobs ISSUE 9 names as previously undeclared are now
    registered with docs."""
    from fdtd3d_tpu.config import ENV_KNOBS
    for name in ("FDTD3D_TEST_TPU", "FDTD3D_BENCH_TELEMETRY",
                 "FDTD3D_BENCH_PER_CHIP", "FDTD3D_VMEM_BUDGET_MB",
                 "FDTD3D_FORCE_PAIRED_COMPLEX", "FDTD3D_BENCH_PROFILE",
                 "FDTD3D_NO_PACKED", "FDTD3D_NO_TEMPORAL",
                 "FDTD3D_NO_FUSED", "FDTD3D_FORCE_FUSED",
                 "FDTD3D_FAULT_PLAN"):
        assert name in ENV_KNOBS, name
        knob = ENV_KNOBS[name]
        assert knob.doc.strip(), name
        assert knob.kind in ("flag", "int", "str", "path"), name


# -------------------------------------------------------------------------
# baseline policy + report format
# -------------------------------------------------------------------------

def test_baseline_requires_reasons(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({
        "schema": "fdtd3d-lint-baseline", "version": 1,
        "suppressions": [{"rule": "no-bare-print", "file": "x.py",
                          "contains": "print", "reason": "  "}]}))
    with pytest.raises(ValueError, match="empty reason"):
        load_baseline(str(p))
    p.write_text(json.dumps({"schema": "wrong", "suppressions": []}))
    with pytest.raises(ValueError, match="schema"):
        load_baseline(str(p))


def test_baseline_suppresses_and_reports(tmp_path):
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({
        "schema": "fdtd3d-lint-baseline", "version": 1,
        "suppressions": [{
            "rule": "no-bare-print", "file": "bad_print.py",
            "contains": "print", "reason": "test fixture waiver"}]}))
    rep = run_rules(["no-bare-print"],
                    ctx=_fixture_ctx("bad_print.py"),
                    baseline_path=str(baseline))
    assert rep["clean"]
    assert len(rep["suppressed"]) == 1
    assert rep["rules"]["no-bare-print"]["suppressed"] == 1
    # apply_baseline unit form
    live, sup = apply_baseline(
        [Finding("r", "f.py", 1, "msg here")],
        [{"rule": "r", "file": "f.py", "contains": "msg",
          "reason": "x"}])
    assert not live and len(sup) == 1


def test_checked_in_baseline_is_valid_and_empty():
    """Acceptance: the shipped baseline is empty (or every entry
    carries its justification — load_baseline enforces the reason)."""
    sups = load_baseline(os.path.join(ROOT, "tools",
                                      "lint_baseline.json"))
    assert sups == [], ("the checked-in baseline gained entries; "
                       "each must carry a reviewed reason and the "
                       "repo must still be clean without tier-1 "
                       "regressions")


def test_report_shape_and_roundtrip():
    rep = run_rules(["no-bare-print", "exception-hygiene"])
    assert rep["schema"] == REPORT_SCHEMA and rep["version"] == 1
    for name in ("no-bare-print", "exception-hygiene"):
        row = rep["rules"][name]
        assert set(row) == {"engine", "doc", "findings", "suppressed",
                            "stats"}
    rt = json.loads(json.dumps(rep))
    assert rt == rep
    with pytest.raises(ValueError, match="unknown rule"):
        run_rules(["does-not-exist"])


def test_broken_rule_fails_the_gate(monkeypatch):
    """A crashing rule must surface as analysis-error, never a silent
    pass."""
    from fdtd3d_tpu.analysis import ast_rules

    def boom(self, ctx):
        raise RuntimeError("kaboom")

    monkeypatch.setattr(ast_rules.NoBarePrintRule, "run", boom)
    rep = run_rules(["no-bare-print"])
    assert not rep["clean"]
    assert rep["findings"][0]["rule"] == "analysis-error"
    assert "kaboom" in rep["findings"][0]["message"]
