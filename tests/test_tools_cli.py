"""Tier-1 tools-CLI smoke (ISSUE 7 satellite, CI/tooling).

The per-tool tests exercise library functions through importlib; what
they MISS is rot in the CLI surface itself — a broken import, a
renamed flag, an argparse typo — which only shows up when the script
runs as an operator would run it. This file subprocess-runs every
``tools/*.py``:

* ``--help`` must exit 0 for every maintained tool (quarantined LEGACY
  tools instead prove their gate: exit 2 + the opt-in flag hint);
* every tool with a checked-in fixture also runs ONCE end-to-end on
  it, chip-free.

Chip-bound sweeps (decompose_overhead, measure_lowdim,
accuracy_frontier, weak_scaling) only smoke ``--help`` here — their
measurement bodies are chip-window affairs.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "tools")
FIX = os.path.join(ROOT, "tests", "fixtures")

# Quarantined legacy tools: their CLI contract IS the refusal.
LEGACY = {"measure_r3.py", "measure_r4.py"}

ALL_TOOLS = sorted(os.path.basename(p)
                   for p in glob.glob(os.path.join(TOOLS, "*.py")))


def _run(args, timeout=180):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=ROOT)


def test_tool_listing_is_current():
    """The smoke surface tracks the directory — a new tool cannot be
    added without joining (or explicitly quarantining from) the lane."""
    assert ALL_TOOLS, "tools/ is empty?"
    assert LEGACY <= set(ALL_TOOLS)


@pytest.mark.parametrize("tool",
                         [t for t in ALL_TOOLS if t not in LEGACY])
def test_every_tool_help_exits_zero(tool):
    proc = _run([os.path.join(TOOLS, tool), "--help"])
    assert proc.returncode == 0, (tool, proc.stdout, proc.stderr)
    assert "usage" in proc.stdout.lower(), (tool, proc.stdout)


@pytest.mark.parametrize("tool", sorted(LEGACY))
def test_legacy_tools_refuse_without_flag(tool):
    proc = _run([os.path.join(TOOLS, tool), "--help"])
    assert proc.returncode == 2, (tool, proc.stdout, proc.stderr)
    assert "--i-know-this-is-legacy" in proc.stderr, tool


# -------------------------------------------------------------------------
# one fixture-driven end-to-end run per fixture-capable tool
# -------------------------------------------------------------------------

def test_telemetry_report_runs_on_fixtures():
    for fixture in ("telemetry_v2.jsonl", "telemetry_v4.jsonl",
                    "telemetry_v5.jsonl", "telemetry_v6.jsonl",
                    "telemetry_v7.jsonl", "queue_v8.jsonl",
                    "telemetry_v9.jsonl", "telemetry_v10.jsonl",
                    "queue_v11.jsonl"):
        proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                     os.path.join(FIX, fixture), "--json"])
        assert proc.returncode == 0, (fixture, proc.stderr)
        json.loads(proc.stdout)  # --json emits parseable summaries
    # the v5 text form names the implicated chip and topology rung
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "telemetry_v5.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "TOPOLOGY CHANGE" in proc.stdout
    assert "[chip 3, host 0]" in proc.stdout
    # the v6 text form names the unhealthy batch lane + compile wall
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "telemetry_v6.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "batch: 3 lanes" in proc.stdout
    assert "lane 1" in proc.stdout
    assert "compile:" in proc.stdout
    # the v7 text form prints the SLO alert records (rule id +
    # firing window) in the survived-events summary
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "telemetry_v7.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "ALERT [straggler-ratio] fired over (8, 8]" in proc.stdout
    assert "2 SLO alert(s) fired" in proc.stdout
    # the v9 text form names the per-LANE straggler chip and the
    # trace-plane span census (trace_id + per-phase counts)
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "telemetry_v9.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "per-chip[lane 0]" in proc.stdout
    assert "trace_id=t-00aa11bb22cc33dd" in proc.stdout
    # the v10 text form prints heartbeat coverage per emitter and the
    # LIVENESS verdicts in the survived-events summary
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "telemetry_v10.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "heartbeats[run]: 2 beat(s)" in proc.stdout
    assert "heartbeats[supervisor]: 1 beat(s)" in proc.stdout
    assert "LIVENESS STUCK: scheduler" in proc.stdout
    assert "1 LIVENESS flag(s)" in proc.stdout
    # the v11 text form prints the lease lineage (acquire, fenced
    # takeover, release) and the per-scheduler job-row census
    proc = _run([os.path.join(TOOLS, "telemetry_report.py"),
                 os.path.join(FIX, "queue_v11.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "ACQUIRE worker-0:7001:1786100000 token=1" in proc.stdout
    assert "TAKEOVER worker-1:7002:1786100050" in proc.stdout
    assert "RELEASE worker-1:7002:1786100050 token=2" in proc.stdout
    assert "jobs by scheduler" in proc.stdout


def test_fleet_watch_runs_on_fixture(tmp_path):
    """tools/fleet_watch.py --once on the v10 fixture: the completed
    run retires its emitters (no liveness flag even far in the
    future), while the continuous SLO pass catches the fixture's
    retry+rollback recovery burst, and the exposition refreshes."""
    tool = os.path.join(TOOLS, "fleet_watch.py")
    metrics = str(tmp_path / "watch.prom")
    proc = _run([tool, "--telemetry",
                 os.path.join(FIX, "telemetry_v10.jsonl"),
                 "--once", "--now", "1786200000", "--json",
                 "--metrics", metrics])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["liveness"] == []  # run_end retired all emitters
    assert list(rep["slo"].values()) == ["VIOLATION"]
    assert any(a["rule"] == "recovery-rate" for a in rep["alerts"])
    exposition = open(metrics).read()
    assert 'heartbeats_total{emitter="run"} 2' in exposition
    assert exposition.endswith("# EOF\n")


def test_trace_export_runs_on_fixtures(tmp_path):
    """tools/trace_export.py: the three-stream join renders the v9
    fixture + the v8 queue journal as one Chrome-trace JSON; a
    pre-v9 stream (no spans) is a clean no-op, not an error."""
    tool = os.path.join(TOOLS, "trace_export.py")
    out = str(tmp_path / "trace.json")
    proc = _run([tool, os.path.join(FIX, "queue_v8.jsonl"),
                 "--telemetry", os.path.join(FIX, "telemetry_v9.jsonl"),
                 "--out", out])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    export = json.load(open(out))
    assert export["traceEvents"]
    assert "t-00aa11bb22cc33dd" in export["fdtd3d_traces"]
    proc = _run([tool, "--telemetry",
                 os.path.join(FIX, "telemetry_v2.jsonl")])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # v10 health rows render as instant events on the owning track,
    # time-rebased against the trace's span envelope
    proc = _run([tool, "--telemetry",
                 os.path.join(FIX, "telemetry_v10.jsonl"),
                 "--out", out])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "4 health mark(s)" in proc.stdout
    marks = [e for e in json.load(open(out))["traceEvents"]
             if e.get("ph") == "i"]
    assert sorted(m["name"] for m in marks) == \
        ["heartbeat:run", "heartbeat:run", "heartbeat:supervisor",
         "liveness:stuck"]
    assert all(m["cat"] == "health" and m["s"] == "t" for m in marks)


def test_slo_gate_runs_on_fixtures(tmp_path):
    """tools/slo_gate.py: exit-code contract on the fixture corpus —
    the v7 stream (straggler ratio 3.0, one retry in 8 steps) fires
    VIOLATION/exit 1; the quiet v2 stream gates clean."""
    tool = os.path.join(TOOLS, "slo_gate.py")
    proc = _run([tool, os.path.join(FIX, "telemetry_v7.jsonl")])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "straggler-ratio" in proc.stdout
    assert "VIOLATION" in proc.stdout
    proc = _run([tool, os.path.join(FIX, "telemetry_v2.jsonl")])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # --json round-trips; every rule row carries an explicit status
    proc = _run([tool, os.path.join(FIX, "telemetry_v7.jsonl"),
                 "--json"])
    assert proc.returncode == 1
    out = json.loads(proc.stdout)
    assert out[0]["status"] == "VIOLATION"
    assert all(r["status"] in ("OK", "VIOLATION", "INCONCLUSIVE",
                               "SKIPPED")
               for s in out for r in s["results"])


def test_fleet_report_runs_on_fixture():
    """tools/fleet_report.py: fold the registry fixture + join the
    telemetry fixtures it points at (relative paths resolve against
    the registry's directory)."""
    tool = os.path.join(TOOLS, "fleet_report.py")
    proc = _run([tool, os.path.join(FIX, "registry_v7.jsonl"),
                 "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rollup = json.loads(proc.stdout)
    fleet = rollup["fleet"]
    assert fleet["by_status"] == {"recovered": 2, "running": 1}
    assert {"run": "r20260804T110302-4243-0-1b2c", "lane": 1,
            "first_unhealthy_t": 8} in fleet["unhealthy_tenants"]
    assert any(a["rule"] == "straggler-ratio"
               for a in fleet["alerts"])
    assert {s["chip"] for s in fleet["straggler_leaderboard"]} == \
        {0, 5}
    assert fleet["run_mcells_per_s"]["max"] == 4.8
    # text form names the tenant and the straggler
    proc = _run([tool, os.path.join(FIX, "registry_v7.jsonl")])
    assert proc.returncode == 0, proc.stderr
    assert "UNHEALTHY TENANT" in proc.stdout
    assert "straggler chip" in proc.stdout
    # a missing registry is a friendly exit 1
    proc = _run([tool, os.path.join(FIX, "nope.jsonl")])
    assert proc.returncode == 1
    assert "no such registry" in proc.stderr


def test_fdtd_queue_status_runs_on_fixture(tmp_path):
    """tools/fdtd_queue.py: status folds the checked-in v8 journal
    fixture (the operator's queue table), --json round-trips, and a
    journal-less dir / missing queue-dir exit 1 with named errors."""
    import shutil
    qdir = tmp_path / "queue"
    qdir.mkdir()
    shutil.copy(os.path.join(FIX, "queue_v8.jsonl"),
                str(qdir / "journal.jsonl"))
    tool = os.path.join(TOOLS, "fdtd_queue.py")
    proc = _run([tool, "status", "--queue-dir", str(qdir)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "completed=2" in proc.stdout and "failed=1" in proc.stdout
    assert "lane 1 non-finite" in proc.stdout
    proc = _run([tool, "status", "--queue-dir", str(qdir), "--json"])
    assert proc.returncode == 0, proc.stderr
    jobs = json.loads(proc.stdout)["jobs"]
    assert jobs["j-00002-cc33"]["status"] == "completed"
    assert jobs["j-00002-cc33"]["run_id"] == \
        "r20260804T120009-5002-0-11ee"
    # an empty queue dir is a friendly exit 1
    proc = _run([tool, "status", "--queue-dir",
                 str(tmp_path / "empty")])
    assert proc.returncode == 1
    assert "no journal" in proc.stderr
    # no --queue-dir and no FDTD3D_JOB_QUEUE_DIR: named exit 1
    env = {k: v for k, v in os.environ.items()
           if k != "FDTD3D_JOB_QUEUE_DIR"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, tool, "status"],
                          capture_output=True, text=True,
                          timeout=120, env=env, cwd=ROOT)
    assert proc.returncode == 1
    assert "FDTD3D_JOB_QUEUE_DIR" in proc.stderr
    # the queue-wait SLO rule reads the same fixture journal
    proc = _run([os.path.join(TOOLS, "slo_gate.py"),
                 str(qdir / "journal.jsonl")])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue-wait-p95" in proc.stdout


def test_fdtd_queue_lease_columns_and_compact_on_fixture(tmp_path):
    """tools/fdtd_queue.py on the checked-in v11 journal: status
    renders the lease + fencing columns (LEASE holder/token, STALE
    rejects, per-job fence= stamps), --json carries the fold's lease
    state, and compact succeeds on the released journal with the
    folded state intact afterwards."""
    import shutil
    qdir = tmp_path / "queue"
    qdir.mkdir()
    shutil.copy(os.path.join(FIX, "queue_v11.jsonl"),
                str(qdir / "journal.jsonl"))
    tool = os.path.join(TOOLS, "fdtd_queue.py")
    proc = _run([tool, "status", "--queue-dir", str(qdir)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "completed=2" in proc.stdout
    assert "LEASE worker-1:7002:1786100050 token=2" in proc.stdout
    assert "released" in proc.stdout
    assert "takeover_from=worker-0:7001:1786100000" in proc.stdout
    assert "STALE 1 fenced-out" in proc.stdout
    assert "fence=2 sched=worker-1:7002:1786100050" in proc.stdout
    proc = _run([tool, "status", "--queue-dir", str(qdir), "--json"])
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["max_token"] == 2 and out["stale_rejected"] == 1
    assert out["lease"]["released"] is True
    assert all(j["status"] == "completed"
               for j in out["jobs"].values())
    # the lease is released: compact folds the journal down and the
    # re-folded state is identical (minus the dropped stale rows)
    proc = _run([tool, "compact", "--queue-dir", str(qdir), "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    stats = json.loads(proc.stdout)
    assert stats["rows_after"] < stats["rows_before"]
    assert stats["max_token"] == 2
    proc = _run([tool, "status", "--queue-dir", str(qdir), "--json"])
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["max_token"] == 2 and out["stale_rejected"] == 0
    assert out["lease"]["released"] is True
    assert all(j["status"] == "completed"
               for j in out["jobs"].values())


def test_ckpt_inspect_runs_and_verifies(tmp_path):
    """tools/ckpt_inspect.py: inspect + --verify exit codes on a real
    snapshot, a corrupted one, and an uncommitted directory."""
    import numpy as np
    sys.path.insert(0, ROOT)
    from fdtd3d_tpu import io
    ck = str(tmp_path / "ckpt_t000008.npz")
    io.save_checkpoint(
        {"E": {"Ez": np.arange(64, dtype=np.float32).reshape(8, 8)}},
        ck, extra={"t": 8, "scheme": "2D_TMz", "size": [8, 8, 1],
                   "topology": [2, 1, 1], "psi_slabs": {},
                   "dtype": "float32", "step_kind": "jnp",
                   "state_keys": ["E"],
                   "supervisor": {"topology": [2, 1, 1],
                                  "topology_rung": 1, "retries": 0,
                                  "rollbacks": 1, "degrades": 0,
                                  "env_pins": {}}})
    tool = os.path.join(TOOLS, "ckpt_inspect.py")
    proc = _run([tool, ck, "--verify"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "VERDICT: OK" in proc.stdout
    assert "topology=[2, 1, 1]" in proc.stdout
    assert "supervisor state" in proc.stdout

    proc = _run([tool, ck, "--verify", "--json"])
    out = json.loads(proc.stdout)
    assert out["ok"] and out["checks"]["payload"]
    assert out["meta"]["supervisor"]["topology_rung"] == 1

    # damaged payload: --verify exits 1 naming the failed check
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 2)
    proc = _run([tool, ck, "--verify"])
    assert proc.returncode == 1, proc.stdout
    assert "FAILED" in proc.stdout

    # uncommitted directory snapshot: exit 1, partial set named
    d = str(tmp_path / "ckpt_t000016")
    os.makedirs(d)
    io.publish_host_marker(d, 0, 2)
    proc = _run([tool, d])
    assert proc.returncode == 1, proc.stdout
    assert "NOT COMMITTED" in proc.stdout

    # a missing path is a friendly exit 1, not a traceback
    proc = _run([tool, str(tmp_path / "nope.npz")])
    assert proc.returncode == 1
    assert "no such snapshot" in proc.stderr


def test_trace_attribution_runs_on_fixtures(tmp_path):
    out = tmp_path / "attr.jsonl"
    proc = _run([os.path.join(TOOLS, "trace_attribution.py"),
                 os.path.join(FIX, "fixture.trace.multicore.json"),
                 "--ledger", os.path.join(FIX, "comm_ref.json"),
                 "--json", "--out", str(out)])
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(out.read_text())
    assert rec["type"] == "attribution"
    assert rec["imbalance"]["straggler"] == "TPU:2"


def test_perf_sentinel_runs_on_fixtures(tmp_path):
    cur = tmp_path / "cur.json"
    cur.write_text(json.dumps({"platform": "cpu"}))
    proc = _run([os.path.join(TOOLS, "perf_sentinel.py"), str(cur),
                 "--best", os.path.join(FIX, "bench_best.json"),
                 "--history", os.path.join(FIX, "bench_history_r*.json"),
                 "--ledger", os.path.join(FIX, "ledger_ref.json"),
                 "--ledger-ref", os.path.join(FIX, "ledger_ref.json"),
                 "--comm", os.path.join(FIX, "comm_ref.json"),
                 "--comm-ref", os.path.join(FIX, "comm_ref.json"),
                 "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict["ledger"]["status"] == "OK"
    assert verdict["comm"]["status"] == "OK"


def test_aot_overlap_runs_on_fixture(tmp_path):
    out = tmp_path / "overlap.json"
    proc = _run([os.path.join(TOOLS, "aot_overlap.py"),
                 "--hlo", os.path.join(FIX, "overlap_ref.hlo"),
                 "--out", str(out)])
    assert proc.returncode == 0, proc.stderr
    art = json.loads(out.read_text())
    assert art["schema"] == "fdtd3d-overlap"
    assert art["windows_with_compute"] == 2


def test_aot_overlap_runs_on_tb_fixture(tmp_path):
    """ISSUE-10 satellite: --hlo on the temporal-blocked scheduled-HLO
    fixture proves the depth-2 (two-plane) exchange lowers async with
    compute inside EVERY window, end-to-end through the real CLI."""
    out = tmp_path / "overlap_tb.json"
    proc = _run([os.path.join(TOOLS, "aot_overlap.py"),
                 "--hlo", os.path.join(FIX, "overlap_tb_ref.hlo"),
                 "--out", str(out)])
    assert proc.returncode == 0, proc.stderr
    art = json.loads(out.read_text())
    assert art["schema"] == "fdtd3d-overlap"
    assert art["sync_collective_permutes"] == 0
    assert art["async_starts"] == 4
    assert art["windows"] == art["windows_with_compute"] == 4


def test_costs_cli_topology_overlap_strategy():
    """ISSUE-10 acceptance: `python -m fdtd3d_tpu.costs --topology
    2,2,2 --overlap` reproduces the planner's decision — the comm lane
    prints the deterministic async two-plane strategy + the modeled
    overlap window, no artifact file needed (bare --overlap)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.run(
        [sys.executable, "-m", "fdtd3d_tpu.costs",
         "--same-size", "16", "--pml-size", "2",
         "--topology", "2,2,2", "--hbm-gbps", "600", "--overlap"],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    led = json.loads(proc.stdout)
    strat = led["comm"]["strategy"]
    assert strat["schedule"] == "async"
    assert strat["split"] == "fused"
    assert led["comm"]["overlap_model"] is not None


def test_fdtd_lint_full_run_is_clean():
    """ISSUE 9 acceptance: tools/fdtd_lint.py exits 0 over the repo
    with ALL rules enabled and the checked-in (empty) baseline — the
    operator form of the tier-1 gate in tests/test_analysis.py. The
    CLI pins the CPU backend + 8 virtual devices itself."""
    proc = _run([os.path.join(TOOLS, "fdtd_lint.py")], timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "CLEAN" in proc.stdout


def test_fdtd_lint_env_registry_json_roundtrips():
    proc = _run([os.path.join(TOOLS, "fdtd_lint.py"),
                 "--rule", "env-registry", "--json"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["schema"] == "fdtd3d-lint-report" and rep["clean"]
    assert rep["rules"]["env-registry"]["stats"]["registered"] >= 11


def test_fdtd_lint_findings_exit_one(tmp_path):
    """Exit-code contract: findings -> 1 (a gate, not a report)."""
    bad = tmp_path / "offender.py"
    bad.write_text("def f(x):\n    print(x)\n")
    proc = _run([os.path.join(TOOLS, "fdtd_lint.py"),
                 "--path", str(tmp_path)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "no-bare-print" in proc.stdout


def test_costs_module_cli_runs():
    """python -m fdtd3d_tpu.costs is the ledger's operator entry —
    smoke the sharded comm-lane form too (8 virtual devices)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                         + " --xla_force_host_platform_device_count=8"
                         ).strip()}
    proc = subprocess.run(
        [sys.executable, "-m", "fdtd3d_tpu.costs", "--kind", "jnp",
         "--same-size", "16", "--pml-size", "2",
         "--topology", "2,2,2", "--hbm-gbps", "600"],
        capture_output=True, text=True, timeout=180, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr
    led = json.loads(proc.stdout)
    assert led["comm"]["per_step"]["halo_attribution"] >= 0.95
