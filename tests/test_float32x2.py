"""Double-single (float32x2) field storage: the ≤1e-6 accuracy rung.

Plain f32's measured long-horizon floor vs f64 is the curl arithmetic
itself (BASELINE.md round-4 accuracy section). float32x2 carries E/H,
the CPML psi recursions, and the TFSF incident line as hi+lo pairs
with error-free-transform arithmetic (ops/ds.py).

Test economics: XLA:CPU under the suite's forced 8-device host split
takes many MINUTES to compile any 3D ds step (measured: the same
compile is ~23 s without the split), so the default suite covers the
ds machinery with the primitive tests (test_ds.py) plus a 1D
end-to-end accuracy run (~2 s); every 3D ds simulation test here is
`slow`-marked (pytest -m slow) and the headline 3D claims are
re-measured every round on the real chip via
tools/accuracy_frontier.py — 6.7e-8 rel-err vs f64 on the official
128³/1000-step frontier config (BASELINE.md float32x2 section).

The f64 references run in THIS process: build_static flips
jax_enable_x64 globally, which is safe here because every other array
carries an explicit f32 dtype.
"""

import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, TfsfConfig)
from fdtd3d_tpu.sim import Simulation

N = 24


def _cavity_cfg(dtype, steps=600, parallel=None, point=False,
                drude=False, use_pallas=None):
    return SimConfig(
        scheme="3D", size=(N, N, N), time_steps=steps, dx=1e-3,
        courant_factor=0.5, wavelength=6e-3, dtype=dtype,
        use_pallas=use_pallas,
        pml=PmlConfig(size=(3, 3, 3)),
        point_source=PointSourceConfig(enabled=point, component="Ez",
                                       position=(12, 10, 14)),
        materials=MaterialsConfig(use_drude=drude, eps_inf=1.5,
                                  omega_p=1e11 if drude else 0.0,
                                  gamma=1e10),
        parallel=parallel or ParallelConfig(),
    )


def _mode_init(sim):
    x = np.arange(N) / N
    init = (np.sin(2 * np.pi * 2 * x)[:, None, None]
            * np.sin(2 * np.pi * 3 * x)[None, :, None]
            * np.ones((1, 1, N))).astype(np.float32)
    sim.set_field("Ez", init)
    return sim


def _hilo(sim, grp, comp):
    lo = {"E": "loE", "H": "loH"}[grp]
    return np.asarray(sim.state[grp][comp], np.float64) \
        + np.asarray(sim.state[lo][comp], np.float64)


def test_ds_1d_matches_f64():
    """1D driven line, 400 steps: the full ds chain (diffs, CPML,
    source oscillator, update) vs f64 at the hi+lo readout — the
    default-suite end-to-end ds accuracy smoke (3D equivalents are
    slow-marked; see module docstring)."""
    def cfg(dtype):
        return SimConfig(
            scheme="1D_EzHy", size=(200, 1, 1), time_steps=400, dx=1e-3,
            courant_factor=0.5, wavelength=20e-3, dtype=dtype,
            pml=PmlConfig(size=(10, 0, 0)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(100, 0, 0)))
    s64 = Simulation(cfg("float64"))
    s64.run()
    sds = Simulation(cfg("float32x2"))
    assert sds.step_kind == "jnp_ds"
    sds.run()
    s32 = Simulation(cfg("float32"))
    s32.run()
    ref = np.asarray(s64.state["E"]["Ez"], np.float64)
    got = _hilo(sds, "E", "Ez")
    f32v = np.asarray(s32.state["E"]["Ez"], np.float64)
    scale = np.abs(ref).max() + 1e-30
    errds = np.abs(got - ref).max() / scale
    err32 = np.abs(f32v - ref).max() / scale
    assert errds < 1e-10, f"ds {errds:.2e}"
    assert errds < err32 / 100.0, f"ds {errds:.2e} vs f32 {err32:.2e}"


@pytest.mark.slow
def test_ds_operator_matches_f64():
    """Source-free cavity + CPML, 600 steps: the ds operator must track
    f64 to ~1e-12 at hi+lo readout (measured 1.7e-13) where plain f32
    drifts to ~2e-6 — the core of the accuracy-rung claim."""
    s64 = _mode_init(Simulation(_cavity_cfg("float64"))).run()
    sds = _mode_init(Simulation(_cavity_cfg("float32x2"))).run()
    assert sds.step_kind == "jnp_ds"
    for comp, grp in (("Ez", "E"), ("Hx", "H")):
        ref = np.asarray(s64.state[grp][comp], np.float64)
        got = _hilo(sds, grp, comp)
        scale = np.abs(ref).max() + 1e-30
        assert np.abs(got - ref).max() < 1e-11 * scale, comp
    # hi-only readout (what consumers get) sits at the eps32/2 floor
    hi = np.asarray(sds.state["E"]["Ez"], np.float64)
    ref = np.asarray(s64.state["E"]["Ez"], np.float64)
    err = np.abs(hi - ref).max() / (np.abs(ref).max() + 1e-30)
    assert err < 2e-7, f"hi-only readout {err:.2e}"


@pytest.mark.slow
def test_ds_point_source_drude_finite():
    """Point source + electric Drude at float32x2 (J stays f32 by
    design): finite fields, engaged kind, lo words populated; and
    set_field resets the lo word so the pair stays consistent.

    use_pallas=True: runs the packed-ds kernel (the production path
    for this config since round 5). The jnp-ds + point-source graph
    effectively never finishes on this host's XLA:CPU (see
    test_pallas_packed_ds's skip-marked parity twin for the record);
    the jnp psrc-ds semantics stay covered by the 1D test above."""
    sim = Simulation(_cavity_cfg("float32x2", steps=120, point=True,
                                 drude=True, use_pallas=True))
    assert sim.step_kind == "pallas_packed_ds"
    sim.run()
    for c, v in sim.fields().items():
        assert np.isfinite(v).all(), c
    lo = np.asarray(sim.state["loE"]["Ez"])
    assert np.isfinite(lo).all()
    assert np.abs(lo).max() > 0.0, "lo words never populated"
    sim.set_field("Ez", np.zeros(sim.cfg.grid_shape, np.float32))
    assert np.abs(np.asarray(sim.state["loE"]["Ez"])).max() == 0.0


@pytest.mark.slow
def test_ds_sharded_matches_unsharded():
    """The ds shift-op halo path (ppermuted neighbor OPERANDS, not
    differences) must reproduce the unsharded ds run on the 8-device
    mesh — same values in, same error-free transforms. Driven by a
    seeded eigenmode rather than a point source: the jnp-ds psrc
    graph never finishes on this host's XLA:CPU (see
    test_ds_point_source_drude_finite), and the jnp-ds sharded path
    is what this test exists to pin (kernel sharding has its own
    parity suite in test_pallas_packed_ds)."""
    ref = _mode_init(Simulation(_cavity_cfg("float32x2", steps=60)))
    ref.run()
    sim = _mode_init(Simulation(_cavity_cfg(
        "float32x2", steps=60,
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 2)))))
    assert sim.step_kind == "jnp_ds"
    sim.run()
    got = sim.fields()
    for c, rv in ref.fields().items():
        scale = np.abs(rv).max() + 1e-30
        assert np.abs(got[c] - rv).max() < 1e-6 * scale, c


@pytest.mark.slow
def test_ds_tfsf_beats_f32_against_f64():
    """The TFSF accuracy claim on CPU (multi-minute XLA:CPU compile —
    see module docstring; the chip-side 128³/1000-step equivalent runs
    every round via tools/accuracy_frontier.py and is the
    authoritative number). The ds leg runs the packed-ds kernel — its
    production path, and a necessity here: the jnp-ds TFSF
    incident-line gathers share the XLA:CPU execution pathology of the
    jnp-ds point source (40 min was not enough for 240 steps at 24³;
    see test_ds_point_source_drude_finite), while the kernel's
    iota-masked in-kernel source adds execute at normal speed. 240
    steps keeps the three-dtype run inside the slow-lane budget; the
    f32-vs-ds gap is already decisive there (f32 source-phase and curl
    drift grow with t, ds does not)."""
    def cfg(dtype):
        return SimConfig(
            scheme="3D", size=(N, N, N), time_steps=240, dx=1e-3,
            courant_factor=0.5, wavelength=N * 1e-3 / 4.0, dtype=dtype,
            use_pallas=(dtype == "float32x2") or None,
            pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(3, 3, 3),
                            angle_teta=30.0, angle_phi=40.0,
                            angle_psi=15.0))

    runs = {}
    for dt in ("float64", "float32", "float32x2"):
        sim = Simulation(cfg(dt))
        if dt == "float32x2":
            assert sim.step_kind == "pallas_packed_ds"
        sim.run()
        runs[dt] = sim.fields()
    comps = list(runs["float64"])
    escale = max(np.abs(runs["float64"][c]).max() for c in comps
                 if c[0] == "E")
    hscale = max(np.abs(runs["float64"][c]).max() for c in comps
                 if c[0] == "H")

    def rel(dt):
        return max(
            np.abs(np.asarray(runs[dt][c], np.float64)
                   - runs["float64"][c]).max()
            / (escale if c[0] == "E" else hscale) for c in comps)

    err32, errds = rel("float32"), rel("float32x2")
    assert err32 > 5e-7, f"f32 unexpectedly accurate: {err32:.2e}"
    assert errds < 2e-7, f"float32x2 rel err {errds:.2e}"
    assert errds < err32 / 5.0
