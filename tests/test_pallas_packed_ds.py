"""Packed double-single kernel (ops/pallas_packed_ds.py) vs jnp-ds.

The float32x2 mode's jnp step is the accuracy gold standard (6.7e-8
vs f64 at 1000 steps, BASELINE.md); the packed-ds kernel must
reproduce it to EFT-reordering tolerance — the only differences are
summation order (the in-kernel slab algebra, x included since round
6, merges ik*dfa + psi into one add_ff chain where jnp-ds adds the
dfa term and the slab fix to the accumulator separately) which is
O(eps^2) per step, far below the mode's own error floor. Vacuum runs
(no slab algebra at all) must be BIT-EXACT: every in-kernel operation
is the same EFT sequence jnp-ds traces.

Out-of-scope configs (a shard too thin for the CPML slabs) must fall
back to jnp_ds rather than silently degrade; Drude (uniform or
sphere), material coefficient grids (streamed operands), and sharded
topologies (pair ghosts + traced source records) are IN scope with
their own parity tests below.

In this CPU test env the kernel runs in interpret mode WITH the
optimization barriers kept (module docstring: interpret-mode bodies
land in the XLA graph where the simplifier folds are real); the
compiled-Mosaic EFT exactness is covered on real TPU by
tests/test_ds.py::test_pallas_eft_exactness.
"""

import jax
import numpy as np
import pytest

from fdtd3d_tpu.config import (MaterialsConfig, ParallelConfig, PmlConfig,
                               PointSourceConfig, SimConfig, SphereConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation

BASE = dict(scheme="3D", size=(16, 16, 16), time_steps=6, dx=1e-3,
            courant_factor=0.4, wavelength=8e-3, dtype="float32x2")


def _seed_fields(sim, seed=0):
    key = jax.random.PRNGKey(seed)
    for grp in ("E", "H"):
        for c in list(sim.state[grp]):
            key, k2 = jax.random.split(key)
            sim.set_field(c, 0.01 * np.asarray(
                jax.random.normal(k2, sim.state[grp][c].shape)))


def _run(use_pallas, **kw):
    sim = Simulation(SimConfig(**BASE, use_pallas=use_pallas, **kw))
    _seed_fields(sim)
    sim.run()
    return sim


def _parity(tol, **kw):
    j = _run(False, **kw)
    p = _run(True, **kw)
    assert p.step_kind == "pallas_packed_ds", p.step_kind
    assert j.step_kind == "jnp_ds", j.step_kind
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(j.field(c), np.float32)
        b = np.asarray(p.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < tol, f"{c}: rel {rel:.2e}"
    # the LO words must agree too — they carry the accuracy claim
    for grp, lo in (("E", "loE"), ("H", "loH")):
        for c in j.state[lo]:
            a = np.asarray(j.state[lo][c])
            b = np.asarray(p.state[lo][c])
            ref = np.abs(np.asarray(j.state[grp][c])).max() + 1e-30
            rel = np.abs(a - b).max() / ref
            assert rel < tol, f"{lo}/{c}: rel {rel:.2e}"
    return j, p


def test_packed_ds_vacuum_bit_exact():
    _parity(1e-12)


def test_packed_ds_cpml_parity():
    j, p = _parity(1e-9, pml=PmlConfig(size=(3, 3, 3)))
    # psi recursion state (hi and lo) must match as well
    for grp in ("psi_E", "psi_H", "lopsi_E", "lopsi_H"):
        for k in j.state[grp]:
            a = np.asarray(j.state[grp][k])
            b = np.asarray(p.state[grp][k])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 1e-6, f"{grp}/{k}: rel {rel:.2e}"


def test_packed_ds_tfsf_scattered_clean():
    """In-kernel TFSF records, single run against the PHYSICS oracle.

    Axis-aligned incidence: the scattered region outside the TFSF box
    must be clean to the mode's accuracy floor. Any error in the
    in-kernel record machinery (apply_corr's tile gating, stack_terms'
    operand row layout, a sign/plane off-by-one) leaks O(1) incident
    field outside the box; float32x2 must sit ~1e-12, far below f32's
    ~1e-7 floor. One packed-ds run — no slow jnp-ds reference — so the
    intricate path is exercised by the DEFAULT suite (the exact-parity
    twin below is slow-marked)."""
    cfg = SimConfig(scheme="3D", size=(24, 24, 24), time_steps=30,
                    dx=1e-3, courant_factor=0.5, wavelength=6e-3,
                    dtype="float32x2", use_pallas=True,
                    pml=PmlConfig(size=(4, 4, 4)),
                    tfsf=TfsfConfig(enabled=True, margin=(4, 4, 4),
                                    angle_teta=90.0, angle_phi=0.0,
                                    angle_psi=180.0))
    sim = Simulation(cfg)
    assert sim.step_kind == "pallas_packed_ds", sim.step_kind
    sim.run()
    ez = np.asarray(sim.field("Ez"), np.float64)
    tot = np.abs(ez[8:16, 8:16, 8:16]).max()
    sc = np.abs(ez[5:7, 5:19, 5:19]).max()
    assert tot > 1e-3, tot           # the wave actually launched
    assert sc / tot < 1e-10, (sc, tot)


def test_packed_ds_point_source_vs_f32():
    """In-kernel point-source pseudo-record vs the f32 packed kernel.

    The f32 packed path applies the same source post-kernel; agreement
    to ~f32 accumulation error (<<1) catches any gating/one-hot/tile
    indexing bug in the ds pseudo-record, which would be O(1). Both
    paths compile fast (no jnp-ds reference; the exact-parity twin is
    slow-marked)."""
    kw = dict(scheme="3D", size=(16, 16, 16), time_steps=10, dx=1e-3,
              courant_factor=0.4, wavelength=8e-3, use_pallas=True,
              pml=PmlConfig(size=(3, 3, 3)),
              point_source=PointSourceConfig(
                  enabled=True, component="Ez", position=(8, 8, 8)))
    ds_sim = Simulation(SimConfig(dtype="float32x2", **kw))
    assert ds_sim.step_kind == "pallas_packed_ds", ds_sim.step_kind
    ds_sim.run()
    f32_sim = Simulation(SimConfig(dtype="float32", **kw))
    f32_sim.run()
    for c in ("Ez", "Hx", "Hy"):
        a = np.asarray(f32_sim.field(c), np.float64)
        b = np.asarray(ds_sim.field(c), np.float64)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 1e-4, f"{c}: rel {rel:.2e}"


@pytest.mark.slow
def test_packed_ds_checkpoint_resume_bit_exact(tmp_path):
    """Checkpoint/resume through the packed pair carry: the lo words,
    pair psi state, and incident-line pairs must all round-trip — a
    dropped lo word would silently demote the run to f32 accuracy."""
    def mk():
        return Simulation(SimConfig(
            **{**BASE, "time_steps": 0}, use_pallas=True,
            pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                            angle_teta=30.0, angle_phi=40.0,
                            angle_psi=15.0)))
    ckpt = str(tmp_path / "ck.npz")
    a = mk()
    assert a.step_kind == "pallas_packed_ds"
    a.advance(6)
    a.checkpoint(ckpt)
    a.advance(6)
    b = mk()
    b.restore(ckpt)
    assert b.t == 6
    b.advance(6)
    for grp in ("E", "H", "loE", "loH", "lopsi_E", "lopsi_H", "inc"):
        for c in a.state[grp]:
            ref = np.asarray(a.state[grp][c])
            got = np.asarray(b.state[grp][c])
            assert np.array_equal(got, ref), f"{grp}/{c} diverged"


@pytest.mark.slow
def test_packed_ds_tfsf_parity():
    _parity(1e-9, pml=PmlConfig(size=(3, 3, 3)),
            tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                            angle_teta=30.0, angle_phi=40.0,
                            angle_psi=15.0))


@pytest.mark.slow
@pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="the jnp-ds REFERENCE side of this parity "
    "(float32x2 + point source + CPML) stalls on XLA:CPU "
    "specifically (observed >15 min at ~2% CPU, repeatedly) — an "
    "XLA:CPU pathology, not a kernel one, so the only direct "
    "jnp-ds vs kernel point-source+CPML parity runs in the TPU "
    "lane: FDTD3D_TEST_TPU=1 pytest -m slow ... on a chip host "
    "(conftest.py skips its CPU pin then; advisor finding r5-3). "
    "On CPU the machinery is covered by "
    "test_packed_ds_point_source_vs_f32 and "
    "test_packed_ds_sharded_parity (psrc on, packed reference)")
def test_packed_ds_point_source_parity():
    _parity(1e-9, pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(8, 8, 8)))


def test_packed_ds_fallbacks():
    """Out-of-scope configs dispatch to jnp_ds, never silently degrade."""
    # a shard too thin for the CPML slabs (x-local 12 vs 2*(5+1)):
    # thin-grid full-length psi is jnp-ds territory
    sim = Simulation(SimConfig(
        **{**BASE, "size": (24, 24, 24)}, use_pallas=True,
        pml=PmlConfig(size=(5, 5, 5)),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 1, 1))))
    assert sim.step_kind == "jnp_ds", sim.step_kind


_SHARD_KW = dict(pml=PmlConfig(size=(2, 2, 2)),
                 tfsf=TfsfConfig(enabled=True, margin=(2, 2, 2),
                                 angle_teta=30.0, angle_phi=40.0,
                                 angle_psi=15.0),
                 point_source=PointSourceConfig(enabled=True,
                                                component="Ez",
                                                position=(5, 9, 7)))


@pytest.fixture(scope="module")
def _unsharded_ds_fields():
    """Reference: the UNSHARDED packed-ds kernel (itself held to jnp-ds
    parity by the tests above; the jnp-ds+point-source reference's cold
    XLA:CPU compile is minutes-slow — test_float32x2.py docstring)."""
    sim = Simulation(SimConfig(**BASE, use_pallas=True, **_SHARD_KW))
    assert sim.step_kind == "pallas_packed_ds"
    sim.run()
    return sim.fields()


# The (2,2,2) case subsumes the per-axis coverage class (every axis
# sharded: pair ghosts, hi-edge fixes, and traced source records on x,
# y and z at once); the single-axis/two-axis params ride the slow lane
# — the default tier-1 lane is wall-clock-budgeted and these two were
# its largest redundant cost (~70 s of XLA:CPU interpret time).
@pytest.mark.parametrize("topo", [
    pytest.param((2, 1, 1), marks=pytest.mark.slow),
    pytest.param((1, 2, 2), marks=pytest.mark.slow),
    (2, 2, 2),
])
def test_packed_ds_sharded_parity(topo, _unsharded_ds_fields):
    """Sharded packed-ds (pair ghosts, hi-edge pair fix, traced source
    records) vs the unsharded kernel — full sources on.

    The ghost arithmetic is the same EFT sequence on the same values
    (ppermute only moves planes), so parity holds at the pair level
    like the unsharded CPML case."""
    sim = Simulation(SimConfig(
        **BASE, use_pallas=True,
        parallel=ParallelConfig(topology="manual",
                                manual_topology=topo), **_SHARD_KW))
    assert sim.mesh is not None
    assert sim.step_kind == "pallas_packed_ds", sim.step_kind
    sim.run()
    for c in ("Ex", "Ey", "Ez", "Hx", "Hy", "Hz"):
        a = np.asarray(_unsharded_ds_fields[c], np.float32)
        b = np.asarray(sim.field(c), np.float32)
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
        assert rel < 1e-9, f"{c}: rel {rel:.2e}"


def test_packed_ds_drude_parity():
    """In-kernel plain-f32 ADE currents (uniform Drude e+m) vs jnp-ds.

    Tolerance note: the ADE currents are DELIBERATELY plain f32 in ds
    mode (solver._make_ds_step docstring), so a single hi-word ulp
    difference between the two implementations feeds back through J/K
    at f32-relative scale (~6e-8/step) — measured 1.7e-8 at 6 steps.
    That is the mode's intrinsic ADE floor, far below the <=1e-6
    accuracy bar; a real gating/indexing bug would be O(1)."""
    omega = 2.0 * np.pi * 3e8 / BASE["wavelength"]
    j, p = _parity(1e-6, pml=PmlConfig(size=(3, 3, 3)),
                   materials=MaterialsConfig(
                       use_drude=True, eps_inf=1.0,
                       omega_p=0.05 * omega, gamma=0.0,
                       use_drude_m=True, mu_inf=1.0,
                       omega_pm=0.05 * omega, gamma_m=0.0))
    for grp in ("J", "K"):
        for c in j.state[grp]:
            a = np.asarray(j.state[grp][c])
            b = np.asarray(p.state[grp][c])
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 1e-5, f"{grp}/{c}: rel {rel:.2e}"


@pytest.mark.slow
def test_packed_ds_material_grid_parity():
    """Streamed hi+lo coefficient grids (eps sphere) vs jnp-ds.

    Slow lane (tier-1 wall-clock budget): the streamed-pair-operand
    tile/lag index maps it exercises are also crossed by the f32
    material-grid parity and the sharded (2,2,2) run each default
    pass."""
    _parity(1e-9, pml=PmlConfig(size=(3, 3, 3)),
            materials=MaterialsConfig(
                eps=1.0,
                eps_sphere=SphereConfig(enabled=True, value=4.0,
                                        center=(8.0, 8.0, 8.0),
                                        radius=3.0)))
