"""Deterministic fault-injection tests (ISSUE 5 durable-run layer).

The load-bearing acceptance claims:

* KILL-AND-RESUME: a fault plan kills the run between chunks; ``--resume
  auto`` finds the latest COMMITTED checkpoint and finishes the horizon
  with state BIT-IDENTICAL to an uninterrupted run (f32, CPU).
* a crash (injected failure) mid-write never leaves a torn file under
  the final name — the atomic writer's contract.
* a corrupted snapshot is skipped with a friendly error and an older
  committed snapshot is used instead.

Everything here is CPU-deterministic and sleep-free: faults fire on
step/write counters, never wall clock.
"""

import os

import numpy as np
import pytest

from fdtd3d_tpu import faults, io
from fdtd3d_tpu.config import (OutputConfig, PmlConfig, PointSourceConfig,
                               SimConfig)
from fdtd3d_tpu.sim import Simulation


@pytest.fixture(autouse=True)
def _isolated_plan(monkeypatch):
    """Every test starts and ends without an installed fault plan."""
    monkeypatch.delenv("FDTD3D_FAULT_PLAN", raising=False)
    faults.clear()
    yield
    faults.clear()


def _cfg(save_dir, steps=24, every=8, keep=3, **out_kw):
    return SimConfig(
        scheme="2D_TMz", size=(24, 24, 1), time_steps=steps, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 0)),
        output=OutputConfig(save_dir=str(save_dir),
                            checkpoint_every=every,
                            checkpoint_keep=keep, **out_kw))


def _cli_argv(save_dir):
    return ["--2d", "TMz", "--sizex", "24", "--sizey", "24",
            "--sizez", "1", "--time-steps", "24", "--point-source", "Ez",
            "--checkpoint-every", "8", "--save-dir", str(save_dir),
            "--log-level", "0"]


# -------------------------------------------------------------------------
# plan parsing
# -------------------------------------------------------------------------

def test_fault_plan_parse():
    plan = faults.FaultPlan.parse(
        "nan@t=8,field=Ey; preempt@t=16; fail_write@n=2; "
        "corrupt_ckpt@n=1,mode=zero; error@t=4,times=3")
    kinds = [f.kind for f in plan.faults]
    assert kinds == ["nan", "preempt", "fail_write", "corrupt_ckpt",
                     "error"]
    assert plan.faults[0].field == "Ey" and plan.faults[0].t == 8
    assert plan.faults[2].n == 2
    assert plan.faults[3].mode == "zero"
    assert plan.faults[4].times == 3


def test_fault_plan_parse_rejects_junk():
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.FaultPlan.parse("explode@t=3")
    with pytest.raises(ValueError, match="must be an integer"):
        faults.FaultPlan.parse("nan@t=soon")
    with pytest.raises(ValueError, match="unknown fault-plan key"):
        faults.FaultPlan.parse("nan@step=3")
    with pytest.raises(ValueError, match="mode"):
        faults.FaultPlan.parse("corrupt_ckpt@n=1,mode=shred")
    with pytest.raises(ValueError, match="must be an integer"):
        faults.FaultPlan.parse("nan@t=8,chip=three")
    # a key the kind would silently ignore is rejected loudly — the
    # plan would otherwise "prove" a scenario that never ran
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("fail_write@n=2,chip=1")  # host= meant
    with pytest.raises(ValueError, match="does not apply"):
        faults.FaultPlan.parse("preempt@t=8,times=2")


def test_fault_plan_parse_chip_host_scopes():
    """ISSUE 8: the plan grammar names chips and hosts."""
    plan = faults.FaultPlan.parse(
        "nan@t=8,chip=3; host_lost@n=2; fail_write@n=1,host=1")
    assert plan.faults[0].kind == "nan" and plan.faults[0].chip == 3
    assert plan.faults[1].kind == "host_lost" and plan.faults[1].n == 2
    assert plan.faults[2].kind == "fail_write"
    assert plan.faults[2].n == 1 and plan.faults[2].host == 1


def test_nan_chip_scoped_lands_on_named_chip(tmp_path):
    """nan@...,chip=C places the NaN inside chip C's shard, and the
    health trip attributes the failure to that chip (the supervisor
    stamps its v5 records from exc.bad_chip)."""
    from fdtd3d_tpu.config import ParallelConfig
    import dataclasses
    cfg = dataclasses.replace(
        _cfg(tmp_path, steps=24, every=0, check_finite=True),
        size=(32, 32, 1),
        parallel=ParallelConfig(topology="manual",
                                manual_topology=(2, 2, 1)))
    faults.install("nan@t=8,chip=1")
    sim = Simulation(cfg)
    sim.advance(8)                 # injection at this boundary
    with pytest.raises(FloatingPointError, match=r"chip") as ei:
        sim.advance(2)             # short chunk: NaN stays local
    assert ei.value.bad_chip == 1
    assert 1 in ei.value.bad_chips


def test_nan_chip_out_of_range_is_friendly(tmp_path):
    faults.install("nan@t=8,chip=9")
    sim = Simulation(_cfg(tmp_path, every=0))
    with pytest.raises(ValueError, match="chip=9 out of range"):
        sim.advance(8)


# -------------------------------------------------------------------------
# atomic writer under injected write failures
# -------------------------------------------------------------------------

def test_failed_write_leaves_no_partial_file(tmp_path):
    """fail_write fires before publish: the final name is never
    touched and no tmp debris remains."""
    faults.install("fail_write@n=1")
    target = str(tmp_path / "out.json")
    with pytest.raises(faults.InjectedWriteError):
        with io.atomic_open(target) as f:
            f.write("half-written")
    assert not os.path.exists(target)
    assert not any(".tmp." in n for n in os.listdir(tmp_path))
    # the fault is one-shot: the retried write succeeds
    with io.atomic_open(target) as f:
        f.write("complete")
    assert open(target).read() == "complete"


def test_failed_write_keeps_previous_version(tmp_path):
    target = str(tmp_path / "out.json")
    with io.atomic_open(target) as f:
        f.write("version 1")
    faults.install("fail_write@n=1")
    with pytest.raises(faults.InjectedWriteError):
        with io.atomic_open(target) as f:
            f.write("version 2, torn")
    assert open(target).read() == "version 1"


def test_failed_checkpoint_write_keeps_older_snapshot(tmp_path):
    """A checkpoint write that dies mid-flight leaves the previous
    cadence snapshot committed and loadable."""
    sim = Simulation(_cfg(tmp_path))
    sim.advance(8)                      # ckpt_t000008 commits
    faults.install("fail_write@n=1")
    with pytest.raises(faults.InjectedWriteError):
        sim.advance(8)                  # ckpt_t000016 write fails
    faults.clear()
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [8]
    state, extra = io.load_checkpoint(
        os.path.join(str(tmp_path), "ckpt_t000008.npz"))
    assert extra["t"] == 8


# -------------------------------------------------------------------------
# NaN injection trips the health counters
# -------------------------------------------------------------------------

def test_nan_fault_trips_next_chunk(tmp_path):
    faults.install("nan@t=8,field=Ez")
    sim = Simulation(_cfg(tmp_path, check_finite=True))
    sim.advance(8)   # injection happens at this chunk's boundary
    with pytest.raises(FloatingPointError, match=r"\(8, 16\]"):
        sim.advance(8)
    # the snapshot cadence committed BEFORE the injection: still clean
    state, _ = io.load_checkpoint(
        os.path.join(str(tmp_path), "ckpt_t000008.npz"))
    assert np.isfinite(state["E"]["Ez"]).all()


# -------------------------------------------------------------------------
# ACCEPTANCE: kill between chunks -> --resume auto -> bit-identical
# -------------------------------------------------------------------------

def test_kill_and_resume_auto_bit_identical(tmp_path, monkeypatch):
    from fdtd3d_tpu.cli import main
    d_killed = tmp_path / "killed"
    d_clean = tmp_path / "clean"

    # run A: preempted between chunks at t=16 (after ckpt_t000016
    # committed — the hook order advance() guarantees)
    monkeypatch.setenv("FDTD3D_FAULT_PLAN", "preempt@t=16")
    with pytest.raises(faults.SimulatedPreemption):
        main(_cli_argv(d_killed))
    monkeypatch.delenv("FDTD3D_FAULT_PLAN")
    faults.clear()
    assert [t for t, _ in io.find_checkpoints(str(d_killed))] == [16, 8]

    # resume: finds ckpt_t000016, finishes the horizon
    assert main(_cli_argv(d_killed) + ["--resume", "auto"]) == 0

    # uninterrupted reference run
    assert main(_cli_argv(d_clean)) == 0

    a, _ = io.load_checkpoint(
        os.path.join(str(d_killed), "ckpt_t000024.npz"))
    b, _ = io.load_checkpoint(
        os.path.join(str(d_clean), "ckpt_t000024.npz"))
    import jax
    eq = jax.tree.map(lambda x, y: bool(np.array_equal(x, y)), a, b)
    assert all(jax.tree.leaves(eq)), f"diverged components: {eq}"


def test_resume_auto_skips_past_horizon_checkpoint(tmp_path, monkeypatch):
    """save_dir still holds a previous LONGER same-config run's
    snapshots: --resume auto must not adopt a t past this run's
    horizon (it would 'finish' instantly from the old run's state),
    and keep-K rotation must not let the stale ones crowd the live
    run's snapshots out of the window."""
    from fdtd3d_tpu.cli import main
    argv48 = [a if a != "24" else "48" for a in _cli_argv(tmp_path)]
    assert main(argv48) == 0        # leaves ckpt_t000048/40/32
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == \
        [48, 40, 32]

    monkeypatch.setenv("FDTD3D_FAULT_PLAN", "preempt@t=8")
    with pytest.raises(faults.SimulatedPreemption):
        main(_cli_argv(tmp_path))   # 24-step run killed at t=8
    monkeypatch.delenv("FDTD3D_FAULT_PLAN")
    faults.clear()

    assert main(_cli_argv(tmp_path) + ["--resume", "auto"]) == 0
    ts = [t for t, _ in io.find_checkpoints(str(tmp_path))]
    assert {8, 16, 24} <= set(ts), ts   # live snapshots survived keep-K
    _state, extra = io.load_checkpoint(
        os.path.join(str(tmp_path), "ckpt_t000024.npz"))
    assert extra["t"] == 24             # resumed from t=8, not t=48


def test_resume_auto_without_checkpoints_is_friendly(tmp_path):
    from fdtd3d_tpu.cli import main
    with pytest.raises(SystemExit, match="no committed checkpoint"):
        main(_cli_argv(tmp_path) + ["--resume", "auto"])


def test_resume_explicit_corrupt_is_friendly(tmp_path):
    from fdtd3d_tpu.cli import main
    assert main(_cli_argv(tmp_path)) == 0
    ck = os.path.join(str(tmp_path), "ckpt_t000024.npz")
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 2)
    with pytest.raises(SystemExit, match="structure check failed"):
        main(_cli_argv(tmp_path) + ["--resume", ck])


# -------------------------------------------------------------------------
# corrupted snapshots: skipped with a friendly error, older one used
# -------------------------------------------------------------------------

def test_corrupt_newest_skipped_older_used(tmp_path):
    from fdtd3d_tpu.cli import main
    assert main(_cli_argv(tmp_path)) == 0
    newest = os.path.join(str(tmp_path), "ckpt_t000024.npz")
    with open(newest, "r+b") as fh:
        fh.truncate(os.path.getsize(newest) // 2)
    # direct restore: friendly CheckpointCorrupt naming path + check
    sim = Simulation(_cfg(tmp_path, every=0))
    with pytest.raises(io.CheckpointCorrupt,
                       match=r"ckpt_t000024\.npz.*structure check"):
        sim.restore(newest)
    # --resume auto: skips the corrupt newest, resumes from t=16 and
    # re-finishes the horizon (rewriting ckpt_t000024)
    assert main(_cli_argv(tmp_path) + ["--resume", "auto"]) == 0
    state, extra = io.load_checkpoint(newest)
    assert extra["t"] == 24


def test_corrupt_ckpt_fault_detected_by_checksum(tmp_path):
    """The corrupt_ckpt fault damages a COMMITTED snapshot; the
    integrity checks must refuse it."""
    faults.install("corrupt_ckpt@n=1,mode=zero")
    sim = Simulation(_cfg(tmp_path))
    sim.advance(8)
    sim.advance(8)
    faults.clear()
    first = os.path.join(str(tmp_path), "ckpt_t000008.npz")
    fresh = Simulation(_cfg(tmp_path, every=0))
    with pytest.raises(io.CheckpointCorrupt):
        fresh.restore(first)
    # the later (undamaged) snapshot restores fine
    fresh.restore(os.path.join(str(tmp_path), "ckpt_t000016.npz"))
    assert fresh.t == 16


# -------------------------------------------------------------------------
# restore validation satellites (dtype + carry family)
# -------------------------------------------------------------------------

def test_restore_rejects_dtype_mismatch(tmp_path):
    ck = str(tmp_path / "ck.npz")
    Simulation(_cfg(tmp_path, every=0)).checkpoint(ck)
    import dataclasses
    other = dataclasses.replace(_cfg(tmp_path, every=0),
                                dtype="bfloat16")
    with pytest.raises(ValueError, match="dtype"):
        Simulation(other).restore(ck)


def test_restore_rejects_carry_family_mismatch(tmp_path):
    """A checkpoint whose state family (Drude J companions) does not
    match the target config fails the friendly meta guard, not a
    pytree-structure traceback."""
    import dataclasses

    from fdtd3d_tpu.config import MaterialsConfig
    base = _cfg(tmp_path, every=0)
    drude = dataclasses.replace(base, materials=MaterialsConfig(
        use_drude=True, eps_inf=2.0, omega_p=1e10, gamma=1e9))
    ck = str(tmp_path / "ck.npz")
    Simulation(drude).checkpoint(ck)
    with pytest.raises(ValueError, match="carry family"):
        Simulation(base).restore(ck)


# -------------------------------------------------------------------------
# SIGINT parity with SIGTERM (ISSUE 8 satellite): Ctrl-C still emits
# run_end and finalizes traces/sinks
# -------------------------------------------------------------------------

def test_cli_registers_and_restores_sigint_sigterm(tmp_path,
                                                   monkeypatch):
    """cli.main installs SystemExit-raising handlers for BOTH SIGTERM
    (143) and SIGINT (130), and restores the previous handlers on
    every exit (library callers must not inherit ours)."""
    import signal as _signal

    from fdtd3d_tpu.cli import main
    calls = []

    def fake_signal(sig, handler):
        calls.append((sig, handler))
        return _signal.SIG_DFL

    monkeypatch.setattr(_signal, "signal", fake_signal)
    assert main(_cli_argv(tmp_path)) == 0
    for sig, code in ((_signal.SIGTERM, 143), (_signal.SIGINT, 130)):
        ours = [h for s, h in calls if s == sig]
        assert len(ours) == 2, f"register + restore expected for {sig}"
        with pytest.raises(SystemExit) as ei:
            ours[0](sig, None)       # the installed handler
        assert ei.value.code == code
        assert ours[-1] is _signal.SIG_DFL  # previous handler restored


def test_sigint_finalizes_telemetry_run_end(tmp_path):
    """End-to-end through a real process: Ctrl-C (SIGINT) mid-run
    exits 130 AND the telemetry sink still gets its run_end record —
    the same durability SIGTERM already had."""
    import json
    import signal as _signal
    import subprocess
    import sys
    import time
    tpath = tmp_path / "t.jsonl"
    argv = [sys.executable, "-m", "fdtd3d_tpu.cli", "--2d", "TMz",
            "--sizex", "64", "--sizey", "64", "--sizez", "1",
            "--time-steps", "2000000", "--point-source", "Ez",
            "--metrics-every", "8", "--telemetry", str(tpath),
            "--save-dir", str(tmp_path / "out"), "--log-level", "0"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(argv, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if tpath.exists() and '"type": "chunk"' in \
                    tpath.read_text():
                break  # at least one chunk recorded: mid-run for sure
            time.sleep(0.1)
        assert proc.poll() is None, \
            "run ended before SIGINT could be delivered"
        proc.send_signal(_signal.SIGINT)
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:  # pragma: no cover - hung child
            proc.kill()
            proc.wait()
    assert rc == 130, rc
    recs = [json.loads(line) for line in open(tpath)]
    types = [r["type"] for r in recs]
    assert types[0] == "run_start" and types[-1] == "run_end"


# -------------------------------------------------------------------------
# deterministic chaos (tier-1): bounded fixed-seed fault cocktails drawn
# from the FULL plan grammar — the run always either completes BIT-VALID
# (identical to the clean reference) or fails with a named, friendly
# error; committed checkpoints stay loadable either way (ISSUE 8
# satellite, promoted from the slow lane).
# -------------------------------------------------------------------------

# every error class the harness is ALLOWED to surface: each is a named,
# friendly failure an operator can act on — anything else (a raw numpy/
# zip/shard_map traceback) fails the test
_NAMED_FAILURES = (faults.SimulatedPreemption, FloatingPointError,
                   faults.InjectedTransientError,
                   faults.InjectedWriteError, io.CheckpointCorrupt)


def _draw_plan(rng) -> str:
    """1-3 bounded faults drawn from the full plan grammar."""
    entries = []
    for _ in range(int(rng.integers(1, 4))):
        kind = ["error", "nan", "preempt", "fail_write",
                "corrupt_ckpt"][int(rng.integers(0, 5))]
        if kind == "error":
            entries.append(f"error@t={int(rng.integers(4, 20))},"
                           f"times={int(rng.integers(1, 3))}")
        elif kind == "nan":
            field = ["Ez", "Hx", "Hy"][int(rng.integers(0, 3))]
            entries.append(f"nan@t={int(rng.integers(4, 20))},"
                           f"field={field}")
        elif kind == "preempt":
            entries.append(f"preempt@t={int(rng.integers(8, 24))}")
        elif kind == "fail_write":
            entries.append(f"fail_write@n={int(rng.integers(1, 4))}")
        else:
            entries.append(f"corrupt_ckpt@n={int(rng.integers(1, 3))},"
                           f"mode={'zero' if rng.random() < 0.5 else 'truncate'}")
    return "; ".join(entries)


@pytest.fixture(scope="module")
def chaos_reference(tmp_path_factory):
    """The clean (fault-free) run every completed chaos run must match
    bit-for-bit: rollback restores are bit-exact, so supervision never
    changes the physics."""
    d = tmp_path_factory.mktemp("chaos_ref")
    sim = Simulation(_cfg(d, steps=24))
    sim.advance(24)
    return sim.fields()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_chaos_bounded_fixed_seed_tier1(tmp_path, seed, chaos_reference):
    from fdtd3d_tpu.supervisor import RetryPolicy, Supervisor
    rng = np.random.default_rng(seed)
    spec = _draw_plan(rng)
    faults.install(spec)
    cfg = _cfg(tmp_path / "run", steps=24)
    sup = Supervisor(cfg, policy=RetryPolicy(
        max_retries=2, sleep=lambda _s: None))
    try:
        sim = sup.run(interval=8)
        assert sim._t_host == 24, spec
        for comp, ref in chaos_reference.items():
            assert np.array_equal(sim.fields()[comp], ref), (spec, comp)
    except _NAMED_FAILURES as exc:
        assert str(exc), spec        # named AND message-bearing
    finally:
        faults.clear()
    # whatever happened, every COMMITTED snapshot is loadable — except
    # one the plan itself deliberately damaged (corrupt_ckpt), which
    # must fail with the NAMED integrity error, not a raw traceback
    for _t, path in io.find_checkpoints(str(tmp_path / "run")):
        try:
            io.load_checkpoint(path)
        except io.CheckpointCorrupt:
            assert "corrupt_ckpt" in spec, (spec, path)


# -------------------------------------------------------------------------
# chaos (slow lane): randomized fault sequences, seeded
# -------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_random_fault_sequences(tmp_path, seed):
    """Randomized (but seeded) fault cocktails: whatever happens, the
    run either completes under supervision or dies by preemption, and
    every committed checkpoint stays loadable."""
    rng = np.random.default_rng(seed)
    from fdtd3d_tpu.supervisor import RetryPolicy, Supervisor
    entries = []
    if rng.random() < 0.7:
        entries.append(f"error@t={int(rng.integers(4, 20))},"
                       f"times={int(rng.integers(1, 3))}")
    if rng.random() < 0.5:
        entries.append(f"nan@t={int(rng.integers(4, 20))}")
    if rng.random() < 0.3:
        entries.append(f"fail_write@n={int(rng.integers(1, 4))}")
    spec = "; ".join(entries) if entries else "error@t=8"
    faults.install(spec)
    cfg = _cfg(tmp_path / f"chaos{seed}", steps=24)
    sup = Supervisor(cfg, policy=RetryPolicy(
        max_retries=4, sleep=lambda _s: None))
    try:
        sim = sup.run(interval=8)
        assert sim._t_host == 24
    except FloatingPointError:
        pass  # jnp bottom-of-ladder re-raise is a legal outcome
    finally:
        faults.clear()
    for _t, path in io.find_checkpoints(str(tmp_path / f"chaos{seed}")):
        try:
            io.load_checkpoint(path)  # committed => loadable
        except io.CheckpointCorrupt:
            # only acceptable for a snapshot the plan itself damaged
            assert "corrupt_ckpt" in spec, (spec, path)
