"""Native C++ I/O backend vs the pure-Python writers: identical files.

Skipped when the toolchain can't build the shared object; the Python
fallback is then the only (and already-tested) path.
"""

import numpy as np
import pytest

from fdtd3d_tpu import _native, io


@pytest.fixture(scope="module")
def native_lib():
    lib = _native.load()
    if lib is None:
        pytest.skip("native backend unavailable (no toolchain?)")
    return lib


def _py_txt(arr, path):
    with open(path, "w") as f:
        it = np.nditer(arr, flags=["multi_index"])
        for v in it:
            idx = " ".join(str(i) for i in it.multi_index)
            if np.iscomplexobj(arr):
                f.write(f"{idx} {v.real:.9e} {v.imag:.9e}\n")
            else:
                f.write(f"{idx} {float(v):.9e}\n")


def test_raw_roundtrip(native_lib, tmp_path):
    arr = np.random.default_rng(0).normal(size=(5, 7, 3)).astype(np.float64)
    p = str(tmp_path / "a.dat")
    assert _native.write_raw(p, arr)
    back = _native.read_raw(p, arr.shape, arr.dtype)
    np.testing.assert_array_equal(arr, back)


def test_txt_matches_python(native_lib, tmp_path):
    arr = np.random.default_rng(1).normal(size=(4, 3, 2))
    p_nat, p_py = str(tmp_path / "n.txt"), str(tmp_path / "p.txt")
    assert _native.dump_txt(p_nat, arr)
    _py_txt(arr, p_py)
    assert open(p_nat).read() == open(p_py).read()
    back = _native.load_txt(p_nat, arr.shape, np.float64)
    np.testing.assert_allclose(back, arr, rtol=1e-9)


def test_txt_complex(native_lib, tmp_path):
    arr = (np.random.default_rng(2).normal(size=(3, 4))
           + 1j * np.random.default_rng(3).normal(size=(3, 4)))
    p_nat, p_py = str(tmp_path / "nc.txt"), str(tmp_path / "pc.txt")
    assert _native.dump_txt(p_nat, arr)
    _py_txt(arr, p_py)
    assert open(p_nat).read() == open(p_py).read()
    back = _native.load_txt(p_nat, arr.shape, np.complex128)
    np.testing.assert_allclose(back, arr, rtol=1e-9)


def test_bmp_matches_python(native_lib, tmp_path):
    rng = np.random.default_rng(4)
    rgb = rng.integers(0, 255, size=(13, 17, 3), dtype=np.uint8)
    p_nat, p_py = str(tmp_path / "n.bmp"), str(tmp_path / "p.bmp")
    assert _native.encode_bmp(p_nat, rgb)
    with open(p_py, "wb") as f:
        f.write(io._bmp_encode(rgb))
    assert open(p_nat, "rb").read() == open(p_py, "rb").read()


def test_io_module_uses_native(native_lib, tmp_path):
    """dump/load through fdtd3d_tpu.io roundtrips with the native path."""
    arr = np.random.default_rng(5).normal(size=(6, 6, 6)).astype(np.float32)
    p = str(tmp_path / "grid.dat")
    io.dump_dat(arr, p, step=7)
    back = io.load_dat(p)
    np.testing.assert_array_equal(arr, back)
    pt = str(tmp_path / "grid.txt")
    io.dump_txt(arr, pt)
    back_t = io.load_txt(pt, arr.shape, np.float32)
    np.testing.assert_allclose(back_t, arr, rtol=1e-6)
