"""Dump/load + checkpoint/resume tests (reference File/ + DAT-resume parity).

Golden rule from SURVEY.md §4: DAT roundtrips must be bit-exact, and a
checkpoint-restore-resume run must reproduce the uninterrupted run exactly
(deterministic functional core, same platform, same op order).
"""

import os

import numpy as np
import pytest

from fdtd3d_tpu import io
from fdtd3d_tpu.config import (PmlConfig, PointSourceConfig, SimConfig,
                               TfsfConfig)
from fdtd3d_tpu.sim import Simulation


def test_dat_roundtrip_bit_exact(tmp_path):
    rng = np.random.default_rng(0)
    for dtype in (np.float32, np.float64, np.complex64):
        arr = rng.standard_normal((5, 7, 3)).astype(dtype)
        if np.issubdtype(dtype, np.complexfloating):
            arr = arr + 1j * rng.standard_normal((5, 7, 3)).astype(dtype)
        p = str(tmp_path / f"a_{np.dtype(dtype).name}.dat")
        io.dump_dat(arr, p, step=42)
        back = io.load_dat(p)
        assert back.dtype == arr.dtype
        assert np.array_equal(back, arr)  # bit-exact


def test_txt_roundtrip(tmp_path):
    arr = np.arange(24, dtype=np.float64).reshape(2, 3, 4) * np.pi
    p = str(tmp_path / "a.txt")
    io.dump_txt(arr, p)
    back = io.load_txt(p, arr.shape)
    np.testing.assert_allclose(back, arr, rtol=1e-9)


def test_bmp_writes_valid_image(tmp_path):
    arr = np.zeros((32, 48, 1))
    arr[10:20, 5:40, 0] = 1.0
    arr[25:, :, 0] = -0.5
    p = str(tmp_path / "cut.bmp")
    io.dump_bmp(arr, p, active_axes=(0, 1))
    w, h = io.load_bmp_size(p)
    assert (w, h) == (32, 48)
    with open(p, "rb") as f:
        assert f.read(2) == b"BM"


def test_checkpoint_resume_bit_exact(tmp_path):
    n = 24
    def mk():
        return Simulation(SimConfig(
            scheme="2D_TMz", size=(n, n, 1), time_steps=0, dx=1e-3,
            courant_factor=0.5, wavelength=10e-3,
            pml=PmlConfig(size=(4, 4, 0)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(n // 2, n // 2, 0))))
    ckpt = str(tmp_path / "ck.npz")
    a = mk()
    a.advance(20)
    a.checkpoint(ckpt)
    a.advance(20)

    b = mk()
    b.restore(ckpt)
    assert b.t == 20
    b.advance(20)
    for comp, ref in a.fields().items():
        got = b.fields()[comp]
        assert np.array_equal(got, ref), f"{comp} diverged after resume"


def test_checkpoint_restore_rejects_wrong_scheme(tmp_path):
    ckpt = str(tmp_path / "ck.npz")
    a = Simulation(SimConfig(scheme="1D_EzHy", size=(16, 1, 1)))
    a.checkpoint(ckpt)
    b = Simulation(SimConfig(scheme="3D", size=(8, 8, 8)))
    with pytest.raises(ValueError, match="scheme"):
        b.restore(ckpt)


def test_cli_dumps_and_checkpoints(tmp_path):
    from fdtd3d_tpu.cli import main
    save = str(tmp_path / "out")
    rc = main(["--2d", "TMz", "--sizex", "24", "--sizey", "24",
               "--sizez", "1", "--time-steps", "20", "--point-source", "Ez",
               "--save-res", "10", "--save-dir", save,
               "--save-formats", "dat,bmp", "--checkpoint-every", "20",
               "--save-materials", "--log-level", "0"])
    assert rc == 0
    files = sorted(os.listdir(save))
    assert "Ez_t000010.dat" in files
    assert "Ez_t000020.bmp" in files
    assert "ckpt_t000020.npz" in files
    assert "eps_Ez.dat" in files
    arr = io.load_dat(os.path.join(save, "Ez_t000020.dat"))
    assert arr.shape == (24, 24, 1)
    assert np.isfinite(arr).all() and np.abs(arr).max() > 0


def test_bmp_roundtrip_decode(tmp_path):
    """The BMP loader (reference BMPLoader analog) inverts the encoder."""
    rng = np.random.default_rng(3)
    rgb = rng.integers(0, 256, size=(13, 10, 3), dtype=np.uint8)
    path = str(tmp_path / "roundtrip.bmp")
    with open(path, "wb") as f:
        f.write(io._bmp_encode(rgb))
    got = io.load_bmp(path)
    np.testing.assert_array_equal(got, rgb)


def test_truncated_bmp_raises_clear_error(tmp_path):
    """A corrupt/truncated BMP must fail with a ValueError naming the
    file, not an opaque frombuffer error (ADVICE r2)."""
    import pytest
    rgb = np.zeros((8, 8, 3), dtype=np.uint8)
    path = str(tmp_path / "trunc.bmp")
    full = io._bmp_encode(rgb)
    with open(path, "wb") as f:
        f.write(full[:len(full) // 2])
    with pytest.raises(ValueError, match="trunc.bmp.*truncated"):
        io.load_bmp(path)


def test_material_init_from_bmp(tmp_path):
    """eps loaded from a BMP image: black -> 1.0, white -> --eps."""
    from fdtd3d_tpu.config import MaterialsConfig, SimConfig
    from fdtd3d_tpu.sim import Simulation

    n = 16
    # columns = x axis, rows = y axis; left half black, right half white
    rgb = np.zeros((n, n, 3), dtype=np.uint8)
    rgb[:, n // 2:, :] = 255
    path = str(tmp_path / "eps.bmp")
    with open(path, "wb") as f:
        f.write(io._bmp_encode(rgb))
    cfg = SimConfig(scheme="2D_TMz", size=(n, n, 1), time_steps=5,
                    materials=MaterialsConfig(eps=4.0, eps_file=path))
    sim = Simulation(cfg)
    from fdtd3d_tpu import materials as mats
    eps = mats.scalar_or_grid("Ez", sim.static.grid_shape, (0, 1), 4.0,
                              None, path)
    assert eps[0, 0, 0] == 1.0, "black must map to vacuum"
    assert eps[n - 1, 0, 0] == 4.0, "white must map to --eps"
    sim.run()  # and the solver runs on it
    for comp, v in sim.fields().items():
        assert np.isfinite(v).all()


def test_material_bmp_size_mismatch_raises(tmp_path):
    from fdtd3d_tpu import materials as mats
    rgb = np.zeros((4, 4, 3), dtype=np.uint8)
    path = str(tmp_path / "bad.bmp")
    with open(path, "wb") as f:
        f.write(io._bmp_encode(rgb))
    with pytest.raises(ValueError, match="image is"):
        mats.scalar_or_grid("Ez", (16, 16, 1), (0, 1), 2.0, None, path)


def test_save_materials_dumps_every_grid(tmp_path):
    """--save-materials writes eps, mu, sigma and Drude grids, all formats."""
    from fdtd3d_tpu.config import (MaterialsConfig, OutputConfig, SimConfig,
                                   SphereConfig)
    from fdtd3d_tpu.sim import Simulation

    cfg = SimConfig(
        scheme="3D", size=(8, 8, 8), time_steps=1,
        materials=MaterialsConfig(
            eps=2.0, use_drude=True, eps_inf=1.5, omega_p=1e11, gamma=1e10,
            drude_sphere=SphereConfig(enabled=True, center=(4, 4, 4),
                                      radius=2),
            use_drude_m=True, mu_inf=1.5, omega_pm=1e11, gamma_m=1e10,
            drude_m_sphere=SphereConfig(enabled=True, center=(4, 4, 4),
                                        radius=2)),
        output=OutputConfig(save_materials=True, save_dir=str(tmp_path),
                            formats=("dat", "txt", "bmp")))
    sim = Simulation(cfg)
    io.write_materials(sim)
    names = ([f"eps_{c}" for c in ("Ex", "Ey", "Ez")]
             + [f"omega_p_{c}" for c in ("Ex", "Ey", "Ez")]
             + [f"gamma_{c}" for c in ("Ex", "Ey", "Ez")]
             + [f"mu_{c}" for c in ("Hx", "Hy", "Hz")]
             + [f"omega_pm_{c}" for c in ("Hx", "Hy", "Hz")]
             + [f"gamma_m_{c}" for c in ("Hx", "Hy", "Hz")]
             + ["sigma_e", "sigma_m"])
    for name in names:
        for ext in (".dat", ".txt", ".bmp"):
            assert (tmp_path / (name + ext)).exists(), name + ext
    wp = io.load_dat(str(tmp_path / "omega_p_Ez.dat"))
    assert wp.max() == 1e11 and wp.min() == 0.0


def test_bfloat16_checkpoint_resume(tmp_path):
    """bf16 runs must checkpoint/resume bit-exactly (fields are stored
    widened to f32 in the .npz; bf16 -> f32 -> bf16 is the identity)."""
    cfg = SimConfig(scheme="3D", size=(12, 12, 12), time_steps=20,
                    dtype="bfloat16", pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(enabled=True,
                                                   component="Ez",
                                                   position=(6, 6, 6)))
    sim = Simulation(cfg)
    sim.run(10)
    path = str(tmp_path / "ck.npz")
    sim.checkpoint(path)
    sim.run(10)
    resumed = Simulation(cfg)
    resumed.restore(path)
    assert resumed.state["E"]["Ez"].dtype == __import__("jax").numpy.bfloat16
    resumed.run(10)
    for comp, a in sim.fields().items():
        b = resumed.fields()[comp]
        assert np.array_equal(np.asarray(a, np.float32),
                              np.asarray(b, np.float32)), comp


def test_orbax_checkpoint_resume_sharded_bit_exact(tmp_path):
    """Sharding-aware (orbax) checkpoint on a real mesh: every device's
    shards written without a global gather; resume reproduces the
    uninterrupted run bit-for-bit."""
    pytest.importorskip("orbax.checkpoint")
    from fdtd3d_tpu.config import ParallelConfig

    n = 16
    def mk():
        return Simulation(SimConfig(
            scheme="3D", size=(n, n, n), time_steps=0, dx=1e-3,
            courant_factor=0.5, wavelength=8e-3,
            pml=PmlConfig(size=(3, 3, 3)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(n // 2,) * 3),
            parallel=ParallelConfig(topology="manual",
                                    manual_topology=(2, 2, 2))))
    ckpt = str(tmp_path / "ck_orbax")
    a = mk()
    a.advance(10)
    a.checkpoint(ckpt, backend="orbax")
    assert os.path.isdir(ckpt), "orbax checkpoint must be a directory"
    a.advance(10)

    b = mk()
    b.restore(ckpt)          # backend auto-detected from the directory
    assert b.t == 10
    b.advance(10)
    for comp, ref in a.fields().items():
        got = b.fields()[comp]
        assert np.array_equal(got, ref), f"{comp} diverged (orbax resume)"


def test_checkpoint_truncated_raises_friendly(tmp_path):
    """A truncated .npz raises CheckpointCorrupt naming the path and
    the failed check — never a raw numpy/zipfile traceback."""
    sim = Simulation(SimConfig(scheme="1D_EzHy", size=(16, 1, 1)))
    ck = str(tmp_path / "ck.npz")
    sim.checkpoint(ck)
    with open(ck, "r+b") as fh:
        fh.truncate(os.path.getsize(ck) // 2)
    with pytest.raises(io.CheckpointCorrupt,
                       match=r"ck\.npz.*structure check failed"):
        io.load_checkpoint(ck)
    with pytest.raises(io.CheckpointCorrupt):
        Simulation(SimConfig(scheme="1D_EzHy",
                             size=(16, 1, 1))).restore(ck)


def test_checkpoint_checksum_guards_payload(tmp_path):
    """The metadata carries a payload checksum; zeroing bytes inside an
    array member (with the zip structure kept parseable) is caught by
    the zip CRC or the checksum — one of the named checks, always."""
    rng = np.random.default_rng(0)
    state = {"E": {"Ez": rng.standard_normal((32, 32)).astype(
        np.float32)}}
    ck = str(tmp_path / "ck.npz")
    io.save_checkpoint(state, ck, extra={"t": 0})
    data = bytearray(open(ck, "rb").read())
    data[len(data) // 2] ^= 0xFF  # flip a payload byte in place
    with open(ck, "wb") as fh:
        fh.write(bytes(data))
    with pytest.raises(io.CheckpointCorrupt, match="check failed"):
        io.load_checkpoint(ck)


def test_auto_checkpoint_keep_k_rotation(tmp_path):
    """checkpoint_every/keep-K: only the newest K committed snapshots
    survive the rotation."""
    from fdtd3d_tpu.config import OutputConfig
    cfg = SimConfig(
        scheme="2D_TMz", size=(24, 24, 1), time_steps=30, dx=1e-3,
        courant_factor=0.5, wavelength=10e-3,
        pml=PmlConfig(size=(4, 4, 0)),
        point_source=PointSourceConfig(enabled=True, component="Ez",
                                       position=(12, 12, 0)),
        output=OutputConfig(save_dir=str(tmp_path), checkpoint_every=5,
                            checkpoint_keep=2))
    sim = Simulation(cfg)
    for _ in range(6):
        sim.advance(5)
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == [30, 25]
    assert io.find_latest_checkpoint(str(tmp_path)).endswith(
        "ckpt_t000030.npz")
    # every survivor is a loadable committed snapshot
    for _t, p in io.find_checkpoints(str(tmp_path)):
        io.load_checkpoint(p)


def test_auto_checkpoint_mid_chunk_cadence_resume_bit_exact(tmp_path):
    """Cadence NOT aligned to the chunking (every=7, chunks of 8):
    snapshots land at chunk boundaries past each multiple, and resuming
    from one at a non-chunk-aligned horizon reproduces the
    uninterrupted run bit-exactly."""
    from fdtd3d_tpu.config import OutputConfig

    def mk(save_dir, every):
        return Simulation(SimConfig(
            scheme="2D_TMz", size=(24, 24, 1), time_steps=27, dx=1e-3,
            courant_factor=0.5, wavelength=10e-3,
            pml=PmlConfig(size=(4, 4, 0)),
            point_source=PointSourceConfig(enabled=True, component="Ez",
                                           position=(12, 12, 0)),
            output=OutputConfig(save_dir=str(save_dir),
                                checkpoint_every=every,
                                checkpoint_keep=0)))

    a = mk(tmp_path, 7)
    for n in (8, 8, 8, 3):
        a.advance(n)
    # cadence 7 with chunk ends 8/16/24/27: one snapshot per crossed
    # multiple (7/14/21), at the first boundary past it; 27 crosses no
    # new multiple (28 is never reached)
    assert [t for t, _ in io.find_checkpoints(str(tmp_path))] == \
        [24, 16, 8]
    b = mk(tmp_path / "resume", 0)
    b.restore(os.path.join(str(tmp_path), "ckpt_t000016.npz"))
    assert b.t == 16
    b.advance(11)  # non-chunk-aligned remaining horizon
    for comp, ref in a.fields().items():
        assert np.array_equal(b.fields()[comp], ref), comp


def test_orbax_checkpoint_cross_topology_restore(tmp_path):
    """A topology mismatch is no longer a hard error: the orbax
    restore reassembles the source layout and reshards onto the
    current plan (reshard-on-resume; topology-portable snapshots)."""
    pytest.importorskip("orbax.checkpoint")
    from fdtd3d_tpu.config import ParallelConfig

    cfg = SimConfig(scheme="3D", size=(16, 16, 16), time_steps=8,
                    pml=PmlConfig(size=(3, 3, 3)),
                    point_source=PointSourceConfig(
                        enabled=True, component="Ez", position=(8, 8, 8)),
                    parallel=ParallelConfig(topology="manual",
                                            manual_topology=(2, 1, 1)))
    a = Simulation(cfg)
    a.advance(8)
    ckpt = str(tmp_path / "ck")
    a.checkpoint(ckpt, backend="orbax")
    b = Simulation(SimConfig(scheme="3D", size=(16, 16, 16),
                             time_steps=8, pml=PmlConfig(size=(3, 3, 3)),
                             point_source=PointSourceConfig(
                                 enabled=True, component="Ez",
                                 position=(8, 8, 8))))
    b.restore(ckpt)
    assert b.t == 8
    for comp, ref in a.fields().items():
        assert np.array_equal(b.fields()[comp], ref), comp
