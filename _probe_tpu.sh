#!/bin/bash
cd /root/repo
for i in $(seq 1 40); do
  if timeout 120 python -c "import jax; print(jax.devices())" >/tmp/tpu_probe.log 2>&1; then
    echo "TPU back at attempt $i: $(date)" >> /tmp/tpu_probe.log
    timeout 500 python bench.py >> /tmp/tpu_probe.log 2>&1
    exit 0
  fi
  sleep 60
done
echo "TPU never came back" >> /tmp/tpu_probe.log
exit 1
